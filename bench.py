"""Driver benchmark over the BASELINE workload configs.

Emits one JSON line per measured config, with the primary line — BASELINE
config 4's GPT per-chip slice — printed LAST (the driver records the final
line as the headline metric):

  config 2  ResNet-50 data-parallel        -> imgs/sec/chip
  config 3  BERT-base pretraining, AMP O2  -> tokens/sec/chip
  config 5  ERNIE-3.0 via pipeline step    -> tokens/sec/chip
  config 4  GPT decoder LM (PRIMARY)       -> tokens/sec/chip + MFU

The reference publishes no numbers (BASELINE.md), so ``vs_baseline``
reports measured MFU / 0.40 — 0.40 MFU being the strong H100+NCCL
Megatron-class utilization the north star asks us to match per chip (raw
FLOPs differ per accelerator; utilization is the comparable quantity).
Non-primary configs compute MFU from XLA's compiled cost analysis.

Single-chip notes: config 2's DP and config 5's pp=4 collapse to degree 1
on one chip — the multi-chip schedules are exercised by the driver's
``dryrun_multichip`` and the CPU-mesh test suite; the bench measures the
per-chip throughput term of the BASELINE metric basket.

Remat is OFF by default for the GPT config: the 254M bench model's
activations fit v5e HBM at this batch, and blanket block remat costs ~25%
step time (see PERF.md). Set BENCH_REMAT=1 for the memory-constrained
configuration.

Self-defense (VERDICT r4 #1): every config is timed over >=3 independent
windows guarded by a roofline floor computed from the compiled step's
FLOPs/bytes; windows slower than BENCH_ANOMALY_FACTOR (4x) the floor are
discarded and retried, and a config that never produces a clean window is
emitted with "anomaly": true plus the discard log. Modeled on the
reference's CI outlier gate (tools/check_op_benchmark_result.py). The pure
selection logic is fault-injection-tested in tests/test_bench_guard.py.

Env: BENCH_SMALL=1 (CPU smoke), BENCH_CONFIGS=gpt|all (default all),
BENCH_LAYERS/HIDDEN/HEADS/SEQ/BATCH/STEPS/REMAT/PEAK_TFLOPS,
BENCH_WINDOWS/ANOMALY_FACTOR/RETRY_WINDOWS (guard knobs),
BENCH_PALLAS_CONV=1 (Pallas-vs-XLA conv A/B: per-shape device-time table
at the top-3 ResNet byte shapes + the full-graph ResNet step with
FLAGS_pallas_conv=1 — the table VERDICT r5 asks the next chip round for),
BENCH_TELEMETRY=0 (skip the telemetry overhead A/B), BENCH_TRACE_OUT
(path for the run's step-timeline JSONL, default BENCH_timeline.jsonl —
render with tools/trace_view.py), BENCH_MULTISLICE=0 (skip the 2-slice
hierarchical-vs-flat DCN reduction dryrun), BENCH_SERVE=0 (skip the serving-engine
sweep; BENCH_SERVE_REQUESTS/MAX_NEW/LAYERS/HIDDEN/HEADS/VOCAB size it —
continuous batching vs the sequential one-shot Predictor on one ragged
trace, concurrency sweep, compile-budget/O001 gate; emits
serving_tokens_per_s + serving_p50_ms/serving_p99_ms and appends the
per-request phase records to the timeline JSONL; the resilience leg
additionally runs the subprocess serve drill — SIGKILL mid-decode +
mid-spill, exactly-once replay — and a fault-injected overload trace
with deadlines/bounded admission/shedding, emitting
serving_slo_attainment_pct + serving_shed_rate with the drill recovery
stats; the engine surviving pool exhaustion is asserted).
"""

from __future__ import annotations

import functools
import json
import os
import re
import subprocess
import sys
import time

import numpy as np


def _peak_flops(dev) -> float:
    """Peak bf16 FLOPs for the chip (v5e default; override BENCH_PEAK_TFLOPS)."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = getattr(dev, "device_kind", "").lower()
    table = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12,
             "v5p": 459e12, "v6e": 918e12, "v6 lite": 918e12}
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12


# ---------------------------------------------------------------------------
# Self-defending measurement (VERDICT r4 missing #3 / next-round #1).
#
# The round-4 driver capture recorded BERT at 0.048x — a 25x collapse from a
# transient tunnel/TPU pathology that the bench accepted as truth. Defense,
# modeled on the reference's CI outlier gate (tools/
# check_op_benchmark_result.py — rejects runs outside a tolerance band):
#   1. >=3 independent timing windows per config; the reported number is the
#      min over windows that pass the sanity check.
#   2. A roofline floor computed from the compiled step's FLOPs and bytes
#      (XLA cost analysis): no valid window can beat max(flops/peak,
#      bytes/bw), and a window slower than ANOMALY_FACTOR x that floor is
#      physically implausible for these >=0.3-MFU configs — it is discarded
#      and the window retried.
#   3. If every window is anomalous after retries, the result is still
#      emitted but carries "anomaly": true and the discard log, so the
#      record can never silently present a stalled-tunnel number as a clean
#      measurement.
# The pure window-selection logic (guarded_min) is fault-injection-tested in
# tests/test_bench_guard.py.
# ---------------------------------------------------------------------------

N_WINDOWS = int(os.environ.get("BENCH_WINDOWS", "3"))
ANOMALY_FACTOR = float(os.environ.get("BENCH_ANOMALY_FACTOR", "4.0"))
MAX_EXTRA_WINDOWS = int(os.environ.get("BENCH_RETRY_WINDOWS", "3"))


def _peak_hbm_bw(dev) -> float:
    """Peak HBM bandwidth (bytes/s) for the chip (v5e default)."""
    env = os.environ.get("BENCH_PEAK_HBM_GBS")
    if env:
        return float(env) * 1e9
    kind = getattr(dev, "device_kind", "").lower()
    table = {"v5 lite": 819e9, "v5e": 819e9, "v4": 1228e9,
             "v5p": 2765e9, "v6e": 1640e9, "v6 lite": 1640e9}
    for key, val in table.items():
        if key in kind:
            return val
    return 819e9


def roofline_step_seconds(flops, bytes_accessed, peak_flops, peak_bw):
    """Lower-bound step time from compiled cost: max of the compute and
    memory rooflines. 0.0 when neither quantity is known (guard disabled)."""
    t = 0.0
    if flops and peak_flops:
        t = max(t, flops / peak_flops)
    if bytes_accessed and peak_bw:
        t = max(t, bytes_accessed / peak_bw)
    return t


def _roofline_for(dev, flops, nbytes):
    """Roofline floor for the guard — only on TPU, where the peak tables
    apply (a CPU smoke run would flag every window against a v5e peak)."""
    if getattr(dev, "platform", "") != "tpu":
        return 0.0
    return roofline_step_seconds(flops, nbytes, _peak_flops(dev),
                                 _peak_hbm_bw(dev))


def guarded_min(window_fn, n_windows, roofline_s, factor=None,
                max_extra=None):
    """Collect `n_windows` valid timing windows and return their min.

    window_fn() -> per-step seconds, or None when the window failed to
    measure (e.g. trace did not parse). A window slower than
    factor * roofline_s is an anomaly: it is recorded, discarded, and an
    extra window is attempted (up to n_windows + max_extra total attempts).

    Returns (best_seconds_or_None, anomaly, valid_times, discarded_times):
    anomaly=True means NO clean window was obtained and best is the min of
    the discarded (i.e. untrustworthy) times, or None if nothing measured.
    """
    factor = ANOMALY_FACTOR if factor is None else factor
    max_extra = MAX_EXTRA_WINDOWS if max_extra is None else max_extra
    # Sub-millisecond rooflines (tiny smoke shapes) are dominated by fixed
    # per-step overheads the FLOPs/bytes model can't see — the guard only
    # has meaning for the real >=100 ms configs.
    limit = factor * roofline_s if roofline_s and roofline_s >= 1e-3 \
        else None
    valid, discarded = [], []
    attempts = 0
    while len(valid) < n_windows and attempts < n_windows + max_extra:
        attempts += 1
        t = window_fn()
        if t is None:
            continue
        if limit is not None and t > limit:
            discarded.append(t)
            continue
        valid.append(t)
    if valid:
        return min(valid), False, valid, discarded
    if discarded:
        return min(discarded), True, valid, discarded
    return None, True, valid, discarded


def _measure_guarded(step, state, args, steps, roofline_s,
                     n_windows=None, args_seq=None):
    """Guarded wall + device timing for a donated-state step fn.

    Pre-warm: one compile call + one warm call run before any timed window
    (this is also where Pallas block selection consults the pre-loaded
    autotune cache — never inside a window). Then `n_windows` wall windows
    and `n_windows` device-trace windows, each guarded against the roofline
    floor. Device time is the preferred basis (PERF.md r4: the axon tunnel
    adds ~10-15 ms/dispatch of host latency no real deployment pays).

    args_seq: optional list of per-step arg tuples, cycled across ALL
    steps (warmup included) — a fresh batch per step, so reported losses
    reflect optimization rather than single-batch memorization (VERDICT
    r5 weak #3). Default: `args` every step.

    Returns dict(loss, wall_s, device_s, used_s, timing, anomaly,
    windows, discarded, state).
    """
    n_windows = N_WINDOWS if n_windows is None else n_windows
    seq = list(args_seq) if args_seq else None
    box = {"state": state, "loss": None, "i": 0}

    def next_args():
        if seq is None:
            return args
        a = seq[box["i"] % len(seq)]
        box["i"] += 1
        return a

    loss, state = step(state, *next_args())  # compile
    box["state"] = state
    loss, box["state"] = step(box["state"], *next_args())  # warm
    float(loss)

    def wall_window():
        t0 = time.perf_counter()
        st = box["state"]
        for _ in range(steps):
            loss, st = step(st, *next_args())
        box["loss"] = float(loss)
        box["state"] = st
        return (time.perf_counter() - t0) / steps

    # Wall windows: the guard still applies (a tunnel stall shows up here
    # first), but wall legitimately carries dispatch latency — it is only
    # the fallback basis when no trace parses.
    wall_s, wall_anom, wall_ok, wall_disc = guarded_min(
        wall_window, n_windows, roofline_s)

    def device_window():
        dt, st, loss = _device_step_time(step, box["state"], next_args,
                                         steps)
        box["state"] = st
        if loss is not None:
            box["loss"] = loss
        return dt

    dev_s, dev_anom, dev_ok, dev_disc = guarded_min(
        device_window, n_windows, roofline_s)

    if dev_s is not None and not dev_anom:
        used, timing, anomaly = dev_s, "device", False
    elif wall_s is not None and not wall_anom:
        used, timing, anomaly = wall_s, "wall", False
    else:
        cands = [t for t in (dev_s, wall_s) if t is not None]
        used = min(cands) if cands else None
        timing = "device" if used == dev_s and dev_s is not None else "wall"
        anomaly = True
    return {
        "loss": box["loss"], "wall_s": wall_s, "device_s": dev_s,
        "used_s": used, "timing": timing, "anomaly": anomaly,
        "windows": {"device_ms": [round(t * 1e3, 2) for t in dev_ok],
                    "wall_ms": [round(t * 1e3, 2) for t in wall_ok]},
        "discarded": {"device_ms": [round(t * 1e3, 2) for t in dev_disc],
                      "wall_ms": [round(t * 1e3, 2) for t in wall_disc]},
        "roofline_ms": round(roofline_s * 1e3, 2) if roofline_s else None,
        "state": box["state"],
    }


def _guard_extra(m):
    """The guard fields every emitted config carries."""
    return {
        "anomaly": m["anomaly"], "timing": m["timing"],
        "windows": m["windows"], "discarded": m["discarded"],
        "roofline_ms": m["roofline_ms"],
        "wall_step_ms": round(m["wall_s"] * 1e3, 2) if m["wall_s"] else None,
    }


def _prewarm_autotune():
    """Load the persistent kernel-autotune cache before any timing so
    _pick_blocks-style selectors hit it at trace time (VERDICT r4 #1:
    'pre-warm the autotune cache inside bench before timing')."""
    try:
        from paddle_tpu.ops._pallas.autotune import get_cache
        get_cache().load()
    except Exception:
        pass


def _device_step_time(step, state, args_fn, steps):
    """DEVICE time per step from a profiler trace (hlo_stats total).

    Through the axon tunnel every dispatch costs ~10-15 ms of host latency
    that no real deployment pays (host-local dispatch pipelines ahead of a
    >100 ms device step), so wall-clock under-reports chip throughput.
    args_fn() supplies each step's args (fresh-batch cycling).
    Returns (device_dt, state, loss) — device_dt None when xprof is
    unavailable.
    """
    import shutil
    import tempfile

    import jax

    tracedir = tempfile.mkdtemp(prefix="bench_trace_")
    floss = None
    try:
        loss = None
        with jax.profiler.trace(tracedir):
            for _ in range(steps):
                loss, state = step(state, *args_fn())
            floss = float(loss)  # sync inside the trace window
        from paddle_tpu.profiler.statistic import device_statistics
        st = device_statistics(tracedir, top=1)
        if not st:
            return None, state, floss
        by_cat, _ = st
        total_ms = sum(by_cat.values())
        if not total_ms:
            return None, state, floss
        return total_ms / steps / 1e3, state, floss
    except Exception:
        return None, state, floss
    finally:
        shutil.rmtree(tracedir, ignore_errors=True)


# Per-leg compiled-HLO verify stats (analysis/hlo_check X-rules over the
# leg's own compiled step, measured in _compiled_cost): verifier wall
# time plus the undeclared-collective count — which must stay 0, so
# BENCH_timeline.jsonl tracks both the verifier's cost and any GSPMD
# drift across rounds. Reset per leg; None = the leg compiled nothing.
_HLO_VERIFY = {"hlo_verify_ms": None, "hlo_undeclared_collectives": None}


def _hlo_verify_compiled(compiled):
    """X-rule pass over one compiled bench step. Bench legs declare no
    plan (single-chip programs), so ANY compiled collective counts as
    undeclared — the drift signal the timeline diffs."""
    try:
        from paddle_tpu.analysis import hlo_check, plan_check
        t0 = time.perf_counter()
        diags = hlo_check.check_hlo(plan_check.StepPlan(), compiled,
                                    where="bench.hlo")
        _HLO_VERIFY["hlo_verify_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        _HLO_VERIFY["hlo_undeclared_collectives"] = sum(
            1 for d in diags if d.rule == "X001")
    except Exception:
        _HLO_VERIFY["hlo_verify_ms"] = None
        _HLO_VERIFY["hlo_undeclared_collectives"] = None


def _emit(name, value, unit, mfu, extra):
    import jax
    peak = _peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": name, "value": round(value, 1), "unit": unit,
        "vs_baseline": round(mfu / 0.40, 4) if mfu else 0.0,
        "extra": {**extra, "mfu": round(mfu, 4),
                  "device": str(jax.devices()[0]),
                  "peak_tflops": peak / 1e12,
                  **_HLO_VERIFY},
    }), flush=True)


def _compiled_cost(jitted, *args):
    """(flops, bytes_accessed) from XLA's compiled cost analysis — the
    inputs to the roofline floor the anomaly guard checks against. The
    same compiled executable feeds the leg's X-rule verify
    (_hlo_verify_compiled), so hlo_verify_ms / hlo_undeclared_collectives
    ride along in the leg's emitted extra."""
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:
        return 0.0, 0.0
    _hlo_verify_compiled(compiled)
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return (float(cost.get("flops", 0.0)),
                float(cost.get("bytes accessed", 0.0)))
    except Exception:
        return 0.0, 0.0


# ---------------------------------------------------------------------------
# Config 2: ResNet-50 data parallel (imgs/sec/chip)
# ---------------------------------------------------------------------------

def bench_resnet(small: bool):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import (functional_call,
                                                 get_buffers, get_params)
    from paddle_tpu.nn import functional as F
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.vision.models import resnet18, resnet50

    # batch swept on-chip: 64 -> 1509 imgs/s, 128 -> 1912, 256 -> 2026,
    # 512 -> 1933 (HBM pressure); 256 is the per-chip sweet spot.
    batch = 2 if small else int(os.environ.get("BENCH_RN_BATCH", 256))
    img = 64 if small else 224
    steps = 2 if small else 10
    paddle.seed(0)
    # NHWC: channels ride the 128-lane minor dim; 1x1 convs lower to
    # matmuls (see nn/functional.conv2d fast path) which XLA fuses with
    # the surrounding BN/ReLU elementwise work. Profiled r3 on v5e.
    fmt = os.environ.get("BENCH_RN_FORMAT", "NHWC")
    # MLPerf space-to-depth stem (exact 7x7/s2 rewrite as 4x4/s1 over 2x2
    # s2d input): fills the MXU's input-channel lanes (12 vs 3)
    stem = os.environ.get("BENCH_RN_STEM", "space_to_depth"
                          if fmt == "NHWC" else "conv")
    model = resnet18(num_classes=10, data_format=fmt) if small \
        else resnet50(data_format=fmt, stem_mode=stem)  # small: 18 has no
    # 7x7 stem benefit worth modeling; BENCH_RN_STEM applies to the full run
    model.train()
    model.astype(paddle.bfloat16)
    opt = Momentum(learning_rate=0.1, momentum=0.9, multi_precision=True)
    params = get_params(model)
    buffers = get_buffers(model)
    opt_state = opt.init(params)

    def loss_of(p, buf, x, y):
        out, new_buf = functional_call(model, p, x, buffers=buf, mutable=True,
                                       training=True)
        return F.cross_entropy(out.astype(jnp.float32), y,
                               reduction="mean"), new_buf

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x, y):
        p, buf, st = state
        (loss, new_buf), grads = jax.value_and_grad(
            loss_of, has_aux=True)(p, buf, x, y)
        new_p, new_st = opt.apply_gradients(p, grads, st, 0.1)
        return loss, (new_p, new_buf, new_st)

    rng = np.random.default_rng(0)
    shape = (batch, 3, img, img) if fmt == "NCHW" else (batch, img, img, 3)
    x = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 10 if small else 1000, (batch,)),
                    jnp.int32)
    state = (params, buffers, opt_state)
    dev = jax.devices()[0]
    flops, nbytes = _compiled_cost(step, state, x, y)
    roof = _roofline_for(dev, flops, nbytes)
    m = _measure_guarded(step, state, (x, y), steps, roof)
    dt_used = m["used_s"]
    imgs_s = batch / dt_used
    mfu = flops / dt_used / _peak_flops(dev) if flops else 0.0
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.nn import fused_conv_bn  # noqa: F401  (defines flag)
    from paddle_tpu.ops._pallas import conv as _pconv  # noqa: F401
    _emit("resnet50_dp_imgs_per_sec_per_chip", imgs_s, "imgs/sec/chip", mfu,
          {"loss": m["loss"], "batch": batch, "img": img,
           "step_ms": round(dt_used * 1e3, 2),
           "pallas_conv": int(bool(_flags.flag("pallas_conv"))),
           "fused_conv_bn": int(bool(_flags.flag("fused_conv_bn"))),
           **_guard_extra(m),
           "baseline_config": 2})


# ---------------------------------------------------------------------------
# BENCH_PALLAS_CONV=1: the Pallas-vs-XLA conv A/B VERDICT r5 demands —
# a per-shape device-time table at the top-3 ResNet byte shapes, then the
# full-graph ResNet step with the kernels swapped into the fused units
# ---------------------------------------------------------------------------

def bench_pallas_conv_ab(small: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.nn import fused_conv_bn  # noqa: F401  (defines flag)
    from paddle_tpu.ops._pallas import conv as pconv
    from paddle_tpu.ops._pallas.autotune import _measure

    shapes = [(k, 2 if small else n, h, w, ci, co, s)
              for k, n, h, w, ci, co, s in pconv.RESNET50_TOP3_SHAPES]
    if not small:
        # register block configs in the persistent device-time cache so
        # the full-graph run below traces against tuned blocks
        try:
            pconv.tune_conv_shapes()
        except Exception:
            pass
    rng = np.random.default_rng(0)
    rows = []
    for kind, n, h, w, cin, cout, s_ in shapes:
        k = 1 if kind == "conv1x1" else 3
        pad = (0, 0) if k == 1 else (1, 1)
        stride = (s_, s_)
        x = jnp.asarray(rng.standard_normal((n, h, w, cin)), jnp.bfloat16)
        wgt = jnp.asarray(rng.standard_normal((cout, cin, k, k)) * 0.05,
                          jnp.bfloat16)
        scale = jnp.ones((cin,), jnp.float32)
        shift = jnp.zeros((cin,), jnp.float32)

        pallas_fn = jax.jit(functools.partial(
            pconv.conv2d_fwd, act="relu", stride=stride, padding=pad))

        dn = lax.conv_dimension_numbers(x.shape, wgt.shape,
                                        ("NHWC", "OIHW", "NHWC"))

        @jax.jit
        def xla_fn(x, wgt, scale, shift):
            a = jnp.maximum(x * scale.astype(x.dtype) +
                            shift.astype(x.dtype), 0)
            o = lax.conv_general_dilated(
                a, wgt, stride, [(pad[0], pad[0]), (pad[1], pad[1])],
                dimension_numbers=dn)
            of = o.astype(jnp.float32)
            return o, jnp.sum(of, (0, 1, 2)), jnp.sum(of * of, (0, 1, 2))

        row = {"shape": f"{kind} n{n} {h}x{w} {cin}->{cout} s{s_}"}
        for tag, fn in (("pallas_ms", pallas_fn), ("xla_ms", xla_fn)):
            try:
                row[tag] = round(_measure(
                    lambda: fn(x, wgt, scale, shift), 2, 5), 4)
            except Exception as e:
                row[tag] = None
                row[tag + "_error"] = str(e)[:200]
        if row.get("pallas_ms") and row.get("xla_ms"):
            row["speedup"] = round(row["xla_ms"] / row["pallas_ms"], 3)
        rows.append(row)
    _emit("pallas_conv_shape_ab", len(rows), "shapes", 0.0,
          {"table": rows, "note": "fused fwd (BN prologue + stats "
           "epilogue) per shape, device time; full-graph A/B follows as "
           "resnet50_dp with pallas_conv=1"})
    # full-graph A/B: the same guarded ResNet measurement with the Pallas
    # kernels swapped into the fused_conv_bn units end-to-end
    prev = _flags.get_flags(["fused_conv_bn", "pallas_conv"])
    _flags.set_flags({"fused_conv_bn": 1, "pallas_conv": 1})
    try:
        bench_resnet(small)
    finally:
        _flags.set_flags(prev)


# ---------------------------------------------------------------------------
# Config 3: BERT-base pretraining, AMP O2 (tokens/sec/chip)
# ---------------------------------------------------------------------------

def bench_bert(small: bool):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import functional_call, get_params
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.bert import (BertConfig, BertForPretraining,
                                             bert_tiny)

    # swept on-chip r3: 16 -> 110k tok/s, 32 -> 117k, 64 -> 131k (sweet
    # spot; amortizes fixed costs), 128 -> 113k (HBM pressure)
    batch = 2 if small else int(os.environ.get("BENCH_BERT_BATCH", 64))
    seq = 64 if small else 512
    steps = 2 if small else 10
    paddle.seed(0)
    cfg = bert_tiny() if small else BertConfig(max_position_embeddings=512)
    cfg.hidden_dropout = 0.0
    cfg.attention_dropout = 0.0
    model = BertForPretraining(cfg)
    model.train()
    model.astype(paddle.bfloat16)  # AMP O2: bf16 params + fp32 master
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01, multi_precision=True)
    params = get_params(model)
    opt_state = opt.init(params)

    def loss_of(p, ids, labels, sop):
        return functional_call(model, p, ids, None, None, labels, sop,
                               training=True)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, ids, labels, sop):
        p, st = state
        loss, grads = jax.value_and_grad(loss_of)(p, ids, labels, sop)
        new_p, new_st = opt.apply_gradients(p, grads, st, 1e-4)
        return loss, (new_p, new_st)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    sop = jnp.asarray(rng.integers(0, 2, (batch, 1)), jnp.int32)
    state = (params, opt_state)
    dev = jax.devices()[0]
    flops, nbytes = _compiled_cost(step, state, ids, labels, sop)
    roof = _roofline_for(dev, flops, nbytes)
    m = _measure_guarded(step, state, (ids, labels, sop), steps, roof)
    state = m["state"]
    dt_used = m["used_s"]
    tok_s = batch * seq / dt_used
    mfu = flops / dt_used / _peak_flops(dev) if flops else 0.0

    extra = {"loss": m["loss"], "batch": batch, "seq": seq,
             "step_ms": round(dt_used * 1e3, 2),
             **_guard_extra(m),
             "baseline_config": 3}

    if not small:
        # VERDICT r4 asks #5/#8: masked attention on the flash path (key-
        # bias block) and the PACKED varlen path (segment ids), both at a
        # realistic padding ratio, real-token throughput reported.
        rng2 = np.random.default_rng(1)
        lengths = rng2.integers(seq // 4, seq + 1, batch)
        att = (np.arange(seq)[None, :] < lengths[:, None])
        real = int(att.sum())
        att_j = jnp.asarray(att.astype(np.int32))
        pl_labels = jnp.asarray(np.where(att, np.asarray(labels), -100),
                                jnp.int32)

        def loss_padded(p, ids, att, labels):
            return functional_call(model, p, ids, None, att, labels, None,
                                   training=True)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_padded(state, ids, att, labels):
            p, st = state
            loss, grads = jax.value_and_grad(loss_padded)(p, ids, att,
                                                          labels)
            return loss, (*opt.apply_gradients(p, grads, st, 1e-4),)

        mp = _measure_guarded(step_padded, state, (ids, att_j, pl_labels),
                              steps, roof)
        state, dtp_used = mp["state"], mp["used_s"]

        # pack the SAME real tokens into fewer rows (greedy first-fit)
        rows, row, used = [], [], 0
        srow, snext = [], 1
        for ln in lengths:
            if used + ln > seq:
                rows.append((row, srow))
                row, srow, used, snext = [], [], 0, 1
            row.append(int(ln))
            srow.append(snext)
            used += int(ln)
            snext += 1
        if row:
            rows.append((row, srow))
        n_rows = len(rows)
        ids_np = np.asarray(ids)
        pk_ids = np.zeros((n_rows, seq), np.int32)
        pk_seg = np.zeros((n_rows, seq), np.int32)
        pk_lab = np.full((n_rows, seq), -100, np.int32)
        for r, (lens, segs) in enumerate(rows):
            off = 0
            for ln, sg in zip(lens, segs):
                pk_ids[r, off:off + ln] = ids_np[0, :ln]
                pk_seg[r, off:off + ln] = sg
                pk_lab[r, off:off + ln] = np.asarray(labels)[0, :ln]
                off += ln

        def loss_packed(p, ids, seg, labels):
            return functional_call(model, p, ids, None, None, labels, None,
                                   training=True, packed_segment_ids=seg)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_packed(state, ids, seg, labels):
            p, st = state
            loss, grads = jax.value_and_grad(loss_packed)(p, ids, seg,
                                                          labels)
            return loss, (*opt.apply_gradients(p, grads, st, 1e-4),)

        pk_args = (jnp.asarray(pk_ids), jnp.asarray(pk_seg),
                   jnp.asarray(pk_lab))
        # packed rows < batch → fewer FLOPs; reuse the main roofline only
        # as a permissive floor scaled by row count
        mk = _measure_guarded(step_packed, state, pk_args, steps,
                              roof * n_rows / batch)
        state, dtk_used = mk["state"], mk["used_s"]
        extra.update({
            "padded_anomaly": mp["anomaly"],
            "packed_anomaly": mk["anomaly"],
            "padding_ratio": round(1 - real / (batch * seq), 3),
            "padded_real_tokens_per_sec": round(real / dtp_used, 1),
            "packed_real_tokens_per_sec": round(real / dtk_used, 1),
            "packed_rows": n_rows,
            "padded_step_ms": round(dtp_used * 1e3, 2),
            "packed_step_ms": round(dtk_used * 1e3, 2),
        })

    _emit("bert_base_amp_o2_tokens_per_sec_per_chip", tok_s,
          "tokens/sec/chip", mfu, extra)


# ---------------------------------------------------------------------------
# Config 5: ERNIE through the pipeline train step (tokens/sec/chip)
# ---------------------------------------------------------------------------

def _ernie_pp_probe(pl, params, ids, labels, dev, n_stages, n_micro,
                    steps):
    """Measure the pp schedule MACHINERY on one chip (VERDICT r5 ask #3,
    third carry-over): run the real n_stages-stage 1F1B tick schedule with
    all stages serially resident (pipeline_schedule.spmd_pipeline_serial —
    identical tick/ring/bubble structure, ppermute serialized) against the
    plain microbatch loop over the same stages. The ideal time ratio is
    the bubble, (n_micro + S - 1) / n_micro; anything beyond it is
    schedule machinery (tick scan, ring shifts, output masking), reported
    as pp{S}_machinery_overhead_pct. Rooflines come from each probe
    step's COMPILED executable cost, not the analytic 6N."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.pipeline_schedule import build_serial_probe
    from paddle_tpu.optimizer import AdamW

    probe = build_serial_probe(pl, n_stages, n_micro, remat=True)
    if probe is None:
        return {"error": f"trunk not homogeneous over {n_stages} stages"}
    loss_sched, loss_plain, _ = probe
    opt = AdamW(learning_rate=1e-4, multi_precision=True)

    def make_step(loss_of):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def stp(state, ids, labels):
            p, st = state
            loss, grads = jax.value_and_grad(loss_of)(p, ids, labels)
            return loss, opt.apply_gradients(p, grads, st, 1e-4)
        return stp

    out, times = {}, {}
    for tag, lf in (("plain", loss_plain), ("sched", loss_sched)):
        stp = make_step(lf)
        # fresh param copies per tag: the probe steps donate their state,
        # and the PipelineLayer's own arrays must survive for the main
        # measurement that follows
        p0 = {k: jnp.copy(v) for k, v in params.items()}
        state = (p0, opt.init(p0))
        flops, nbytes = _compiled_cost(stp, state, ids, labels)
        roof = _roofline_for(dev, flops, nbytes)
        m = _measure_guarded(stp, state, (ids, labels), steps, roof,
                             n_windows=2)
        m.pop("state")
        times[tag] = m["used_s"]
        out[tag] = {"step_ms": round(m["used_s"] * 1e3, 2),
                    "timing": m["timing"], "anomaly": m["anomaly"],
                    "roofline_ms": m["roofline_ms"],
                    "compiled_gflops": round(flops / 1e9, 2),
                    "compiled_gb": round(nbytes / 2**30, 3)}
    ratio = (n_micro + n_stages - 1) / n_micro
    overhead = times["sched"] / (times["plain"] * ratio) - 1.0
    out["n_stages"] = n_stages
    out["n_micro"] = n_micro
    out["ideal_bubble_ratio"] = round(ratio, 4)
    out["machinery_overhead_pct"] = round(100.0 * overhead, 2)
    return out


def bench_ernie(small: bool):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import \
        PipelineLayer
    from paddle_tpu.distributed.pipeline_schedule import \
        make_pipeline_train_step
    from paddle_tpu.distributed.topology import (create_hybrid_mesh,
                                                 set_hybrid_mesh)
    from paddle_tpu.framework.functional import get_params
    from paddle_tpu.nn import functional as F
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.ernie import (ernie_base, ernie_tiny,
                                              ernie_pipeline_descs)

    # swept on-chip r3: 16 -> 101k tok/s, 32 -> 109k, 64 -> 120k (sweet
    # spot), 128 -> 95k (HBM pressure)
    batch = 4 if small else int(os.environ.get("BENCH_ERNIE_BATCH", 64))
    seq = 32 if small else 512
    steps = 2 if small else 10
    n_micro = 4
    cfg = ernie_tiny(num_layers=2) if small else \
        ernie_base(max_position_embeddings=512)
    cfg.hidden_dropout = 0.0
    cfg.attention_dropout = 0.0
    paddle.seed(0)
    # One chip: pp degree 1 (the pp=4 schedule itself is validated by
    # dryrun_multichip and the CPU-mesh pipeline tests).
    mesh = create_hybrid_mesh(pp=1, dp=1, devices=jax.devices()[:1])
    set_hybrid_mesh(mesh)

    def loss_fn(logits, labels):
        return jnp.mean(F.cross_entropy(logits.astype(jnp.float32), labels,
                                        reduction="none"))

    pl = PipelineLayer(layers=ernie_pipeline_descs(cfg), num_stages=1,
                       loss_fn=loss_fn)
    pl.astype(paddle.bfloat16)
    opt = AdamW(learning_rate=1e-4, multi_precision=True)
    pstep = make_pipeline_train_step(pl, opt, n_microbatch=n_micro)
    params = get_params(pl)
    opt_state = opt.init(params)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)

    def step(state, ids, labels):
        p, st = state
        p, st, loss = pstep(p, st, ids, labels, jnp.float32(1e-4))
        return loss, (p, st)

    dev = jax.devices()[0]
    n_params = sum(int(np.prod(p.shape)) for p in params.values())
    # pp machinery probe FIRST — it copies params; the main measurement
    # below donates the PipelineLayer's own arrays through pstep.
    pp_stages = 4 if not small else 2
    try:
        pp_probe = _ernie_pp_probe(pl, params, ids, labels, dev,
                                   n_stages=pp_stages, n_micro=n_micro,
                                   steps=max(2, steps // 2))
    except Exception as e:
        pp_probe = {"error": f"{type(e).__name__}: {e}"[:300]}
    # Roofline from the step's COMPILED executable cost (VERDICT r5
    # weak #4: the strongest number had the weakest guard) — the pp=1
    # path of make_pipeline_train_step returns the jitted step itself,
    # so its compiled cost IS reachable; analytic 6N/token is the
    # fallback for the non-lowerable (het-dispatch) variant.
    if hasattr(pstep, "lower"):
        flops, nbytes = _compiled_cost(pstep, params, opt_state, ids,
                                       labels, jnp.float32(1e-4))
    else:
        flops, nbytes = 0.0, 0.0
    if flops:
        roof = _roofline_for(dev, flops, nbytes)
        roof_basis = "compiled"
    else:
        roof = (6 * n_params * batch * seq / _peak_flops(dev)
                if getattr(dev, "platform", "") == "tpu" else 0.0)
        roof_basis = "analytic_6N"
    m = _measure_guarded(step, (params, opt_state), (ids, labels), steps,
                         roof)
    dt_used = m["used_s"]
    tok_s = batch * seq / dt_used
    # Analytic MFU: 6N per token (encoder matmuls + untied MLM head).
    mfu = tok_s * 6 * n_params / _peak_flops(dev)
    set_hybrid_mesh(None)
    _emit("ernie_pipeline_tokens_per_sec_per_chip", tok_s, "tokens/sec/chip",
          mfu,
          {"loss": m["loss"], "batch": batch, "seq": seq, "n_micro": n_micro,
           "n_params": n_params, "step_ms": round(dt_used * 1e3, 2),
           **_guard_extra(m),
           "roofline_basis": roof_basis,
           "pp4_machinery_overhead_pct":
               pp_probe.get("machinery_overhead_pct"),
           "pp4_probe": pp_probe,
           "baseline_config": 5, "pp_degree": 1,
           "note": "single-chip: the pp=4 1F1B tick schedule is measured "
                   "with stages serially resident (pp4_probe); the "
                   "throughput metric runs num_stages=1 (microbatched) — "
                   "one chip cannot host 4 parallel stages"})


# ---------------------------------------------------------------------------
# Telemetry overhead A/B (paddle_tpu/observability): the always-on metrics
# layer must cost <1% step time — measured, not asserted.
# ---------------------------------------------------------------------------

def bench_telemetry_overhead(small: bool):
    """A/B the instrumented ``sharded.TrainStep`` with FLAGS_telemetry=off
    vs =metrics and emit ``telemetry_overhead_pct`` (min-of-windows wall
    per step, identical model/batch/seed both arms). Also exports this
    run's recorded step timeline as JSONL (BENCH_TRACE_OUT, default
    ``BENCH_timeline.jsonl``) — every bench run carries its own timeline,
    viewable with ``tools/trace_view.py``."""
    import jax  # noqa: F401
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.nn import functional as F
    from paddle_tpu.observability import metrics as _omx
    from paddle_tpu.observability import step_monitor
    from paddle_tpu.optimizer import AdamW

    batch = 32 if small else 64
    hidden = 512 if small else 2048
    steps = 20 if small else 30
    windows = 5 if small else 5

    def loss_fn(model, params, b):
        x, y = b
        return F.cross_entropy(functional_call(model, params, x), y).mean()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, hidden)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int64)

    # ONE TrainStep serves both arms (telemetry is host-side only, outputs
    # are bitwise identical — tested in test_observability.py), so the A/B
    # compares the same executable on the same buffers and the arms can be
    # interleaved window-by-window to cancel machine drift.
    timeline = step_monitor.reset_default()  # this A/B's own timeline
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(hidden, hidden), nn.Tanh(),
                        nn.Linear(hidden, hidden), nn.Tanh(),
                        nn.Linear(hidden, 10))
    ts = make_sharded_train_step(net, AdamW(1e-3), loss_fn)
    prev = _flags.get_flags(["telemetry"])
    best = {"off": None, "metrics": None}
    try:
        float(ts.step((x, y)))  # compile + warm
        float(ts.step((x, y)))
        for _ in range(windows):
            for mode in ("off", "metrics"):
                _flags.set_flags({"telemetry": mode})
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = ts.step((x, y))
                float(loss)  # sync the window
                dt = (time.perf_counter() - t0) / steps
                best[mode] = dt if best[mode] is None \
                    else min(best[mode], dt)
    finally:
        _flags.set_flags(prev)
    t_off, t_on = best["off"], best["metrics"]
    overhead_pct = 100.0 * (t_on / t_off - 1.0)

    # timeline export: the per-step records from the metrics arm (plus any
    # earlier instrumented dispatches' series in the metrics snapshot)
    out_path = os.environ.get("BENCH_TRACE_OUT", "BENCH_timeline.jsonl")
    n_records = None
    try:
        n_records = timeline.export_jsonl(out_path)
        from paddle_tpu.observability import trace as _otrace
        n_records += _otrace.export_jsonl(out_path, append=True)
    except Exception:
        pass
    telem_series = {k: v for k, v in _omx.snapshot().items()
                    if k.startswith("telemetry.")}
    _emit("telemetry_overhead_pct", overhead_pct, "pct", 0.0, {
        "overhead_pct": round(overhead_pct, 3),
        "step_ms_off": round(t_off * 1e3, 3),
        "step_ms_metrics": round(t_on * 1e3, 3),
        "steps_per_window": steps, "windows": windows,
        "batch": batch, "hidden": hidden,
        "timeline": timeline.summary(),
        "timeline_jsonl": {"path": out_path, "records": n_records},
        "telemetry_series": telem_series,
        "note": "min-of-windows wall per instrumented sharded.TrainStep "
                "step, FLAGS_telemetry=off vs =metrics, identical "
                "model/batch/seed; view the JSONL with tools/trace_view.py",
    })


def bench_flight_recorder_overhead(small: bool):
    """A/B one instrumented ``sharded.TrainStep`` with
    FLAGS_flight_recorder=off vs =on (recorder armed to a scratch dir,
    FLAGS_telemetry=metrics both arms) and emit
    ``flight_recorder_overhead_pct`` — the crash-persistent black box
    must cost <2% step time on the CPU mesh, measured with interleaved
    windows exactly like the telemetry A/B."""
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.nn import functional as F
    from paddle_tpu.observability import flight_recorder as _flr
    from paddle_tpu.observability import step_monitor
    from paddle_tpu.optimizer import AdamW

    batch = 32 if small else 64
    hidden = 512 if small else 2048
    steps = 20 if small else 30
    windows = 5

    def loss_fn(model, params, b):
        x, y = b
        return F.cross_entropy(functional_call(model, params, x), y).mean()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, hidden)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int64)

    step_monitor.reset_default()
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(hidden, hidden), nn.Tanh(),
                        nn.Linear(hidden, hidden), nn.Tanh(),
                        nn.Linear(hidden, 10))
    ts = make_sharded_train_step(net, AdamW(1e-3), loss_fn)
    run_dir = tempfile.mkdtemp(prefix="bench_flr_")
    box = _flr.arm(run_dir, role="bench", run_id="bench_flight_recorder")
    prev = _flags.get_flags(["flight_recorder", "telemetry"])
    best = {"off": None, "on": None}
    try:
        _flags.set_flags({"telemetry": "metrics"})
        float(ts.step((x, y)))  # compile + warm
        float(ts.step((x, y)))
        for _ in range(windows):
            for mode in ("off", "on"):
                _flags.set_flags({"flight_recorder": mode})
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = ts.step((x, y))
                float(loss)  # sync the window
                dt = (time.perf_counter() - t0) / steps
                best[mode] = dt if best[mode] is None \
                    else min(best[mode], dt)
    finally:
        _flags.set_flags(prev)
        _flr.disarm()
    t_off, t_on = best["off"], best["on"]
    overhead_pct = 100.0 * (t_on / t_off - 1.0)
    _meta, records, replay = _flr.replay(box.path)
    _emit("flight_recorder_overhead_pct", overhead_pct, "pct", 0.0, {
        "overhead_pct": round(overhead_pct, 3),
        "step_ms_off": round(t_off * 1e3, 3),
        "step_ms_on": round(t_on * 1e3, 3),
        "steps_per_window": steps, "windows": windows,
        "batch": batch, "hidden": hidden,
        "recorder_records": len(records),
        "recorder_frames_torn": replay["frames_torn"],
        "recorder_wrapped": replay["wrapped"],
        "note": "min-of-windows wall per instrumented sharded.TrainStep "
                "step, FLAGS_flight_recorder=off vs =on (mmap ring "
                "armed, FLAGS_telemetry=metrics both arms), identical "
                "model/batch/seed; replay the ring with "
                "tools/postmortem.py",
    })


def bench_fleet_telemetry_overhead(small: bool):
    """A/B one instrumented ``sharded.TrainStep`` with
    FLAGS_fleet_telemetry=off vs =on (exporter armed to a scratch dir,
    its daemon thread publishing CRC-framed registry snapshots at the
    default cadence, FLAGS_telemetry=metrics both arms) and emit
    ``fleet_telemetry_overhead_pct`` — the live fleet plane must cost
    <2% step time on the CPU mesh, measured with interleaved windows
    exactly like the recorder A/B above."""
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.nn import functional as F
    from paddle_tpu.observability import live as _live
    from paddle_tpu.observability import step_monitor
    from paddle_tpu.optimizer import AdamW

    batch = 32 if small else 64
    hidden = 512 if small else 2048
    # windows must span several export ticks at the drills' 0.2s
    # cadence, or min-of-windows would just pick an export-free window
    steps = 120 if small else 150
    windows = 4

    def loss_fn(model, params, b):
        x, y = b
        return F.cross_entropy(functional_call(model, params, x), y).mean()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, hidden)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int64)

    step_monitor.reset_default()
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(hidden, hidden), nn.Tanh(),
                        nn.Linear(hidden, hidden), nn.Tanh(),
                        nn.Linear(hidden, 10))
    ts = make_sharded_train_step(net, AdamW(1e-3), loss_fn)
    run_dir = tempfile.mkdtemp(prefix="bench_fleet_")
    prev = _flags.get_flags(["fleet_telemetry", "telemetry"])
    best = {"off": None, "on": None}
    n = {"steps": 0}
    try:
        _flags.set_flags({"telemetry": "metrics"})
        # armed with the thread running BOTH arms: the off arm measures
        # the gate (the thread wakes, sees off, publishes nothing), the
        # on arm the full snapshot+publish path — at the 0.2s cadence
        # the drills themselves arm (FLAGS_fleet_export_interval=0.2)
        exp = _live.arm(run_dir, role="bench", interval_s=0.2)
        float(ts.step((x, y)))  # compile + warm
        float(ts.step((x, y)))
        for _ in range(windows):
            for mode in ("off", "on"):
                _flags.set_flags({"fleet_telemetry": mode})
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = ts.step((x, y))
                    n["steps"] += 1
                    _live.note_progress(n["steps"])
                float(loss)  # sync the window
                dt = (time.perf_counter() - t0) / steps
                best[mode] = dt if best[mode] is None \
                    else min(best[mode], dt)
        snap = _live.read_snapshot(exp.path)
    finally:
        _live.disarm(final_export=False)
        _flags.set_flags(prev)
    t_off, t_on = best["off"], best["on"]
    overhead_pct = 100.0 * (t_on / t_off - 1.0)
    _emit("fleet_telemetry_overhead_pct", overhead_pct, "pct", 0.0, {
        "overhead_pct": round(overhead_pct, 3),
        "step_ms_off": round(t_off * 1e3, 3),
        "step_ms_on": round(t_on * 1e3, 3),
        "steps_per_window": steps, "windows": windows,
        "batch": batch, "hidden": hidden,
        "exports_published": (snap or {}).get("seq"),
        "note": "min-of-windows wall per instrumented sharded.TrainStep "
                "step, FLAGS_fleet_telemetry=off vs =on (exporter "
                "thread armed both arms at the drills' 0.2s cadence, "
                "FLAGS_telemetry=metrics both arms), identical "
                "model/batch/seed; aggregate the snapshots with "
                "tools/fleet_top.py",
    })


# ---------------------------------------------------------------------------
# Config 4 (PRIMARY): GPT decoder LM
# ---------------------------------------------------------------------------

def bench_comm_overlap(small: bool):
    """A/B the communication-overlap tier (FLAGS_comm_overlap): the
    Megatron-SP column/row pair as decomposed bidirectional ppermute
    pipelines vs the GSPMD-scheduled step — same model/seed/batch both
    arms, loss parity asserted, min-of-windows step time per mode. Needs
    >= 2 devices on the mp axis; on a single chip the metric still emits
    the static hop plans (analysis/comm_check) for the next device round.
    """
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.analysis import comm_check
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear,
        sequence_parallel_constraint)
    from paddle_tpu.distributed.topology import (create_hybrid_mesh,
                                                 set_hybrid_mesh)
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.optimizer import AdamW

    # The GPT-1.3B per-layer hop plan (mp=4, bf16) — the A/B shapes the
    # next device round runs, emitted even when this host cannot.
    planned = [
        comm_check.spec_for_allgather_matmul(8, 512, 2048, 2048, 4, 2),
        comm_check.spec_for_matmul_reduce_scatter(8, 512, 2048, 2048, 4, 2),
    ]
    planned_rows = [{
        "op": s.name, "hops": s.hops,
        "bytes_per_hop_mb": round(s.bytes_per_hop / 2**20, 3),
        "diagnostics": [d.rule for d in comm_check.check_comm_spec(s)],
    } for s in planned]

    mp = 1
    while mp * 2 <= min(8, jax.device_count()):
        mp *= 2
    if mp < 2:
        print(json.dumps({
            "metric": "comm_overlap", "value": 0.0, "unit": "ratio",
            "extra": {"skipped": "needs >=2 devices on the mp axis",
                      "devices": jax.device_count(),
                      "planned_specs": planned_rows}}), flush=True)
        return

    d = 64 if small else 256
    seq = mp * (16 if small else 64)
    batch = 4 if small else 8
    steps = 10 if small else 20
    windows = 3

    class SPBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnSequenceParallelLinear(d, 4 * d,
                                                    gather_output=False)
            self.fc2 = RowSequenceParallelLinear(4 * d, d,
                                                 input_is_parallel=True)

        def forward(self, x):
            x = sequence_parallel_constraint(x)
            return self.fc2(jax.nn.gelu(self.fc1(x)))

    class Stack(nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = nn.LayerList([SPBlock() for _ in range(4)])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return x

    def loss_fn(model, params, b):
        x, y = b
        return jnp.mean((functional_call(model, params, x,
                                         training=True) - y) ** 2)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, seq, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal(x.shape), jnp.float32)
    prev = _flags.get_flags(["comm_overlap"])
    results = {}
    try:
        for mode in ("off", "tp"):
            _flags.set_flags({"comm_overlap": mode})
            mesh = create_hybrid_mesh(mp=mp)
            set_hybrid_mesh(mesh)
            paddle.seed(0)
            ts = make_sharded_train_step(Stack(), AdamW(1e-3), loss_fn,
                                         mesh=mesh)
            loss = float(ts.step((x, y)))  # compile + warm
            best = None
            for _ in range(windows):
                t0 = time.perf_counter()
                for _ in range(steps):
                    out = ts.step((x, y))
                float(out)
                dt = (time.perf_counter() - t0) / steps
                best = dt if best is None else min(best, dt)
            results[mode] = {"loss": loss,
                             "step_ms": round(best * 1e3, 3)}
            set_hybrid_mesh(None)
    finally:
        _flags.set_flags(prev)
        set_hybrid_mesh(None)
    parity_ok = abs(results["tp"]["loss"] - results["off"]["loss"]) <= \
        5e-3 * max(1.0, abs(results["off"]["loss"]))
    speedup = results["off"]["step_ms"] / max(results["tp"]["step_ms"],
                                              1e-9)
    print(json.dumps({
        "metric": "comm_overlap", "value": round(speedup, 4),
        "unit": "step-time ratio off/tp",
        "extra": {"modes": results, "parity_ok": bool(parity_ok),
                  "mesh": {"mp": mp}, "shape": {"batch": batch, "seq": seq,
                                                "hidden": d, "blocks": 4},
                  "note": ("CPU-mesh wall times are not ICI-meaningful; "
                           "the device round reads this A/B on real chips"
                           if jax.default_backend() != "tpu" else
                           "device-measured"),
                  "planned_specs": planned_rows}}), flush=True)
    assert parity_ok, (
        f"comm_overlap parity failure: tp loss {results['tp']['loss']} "
        f"vs off {results['off']['loss']}")


def bench_multislice(small: bool):
    """The multi-slice tier (FLAGS_multislice, distributed/multislice):
    a 2-slice x 4-device dryrun on the CPU mesh — the hierarchical
    (ICI reduce-scatter -> DCN allreduce on the 1/ici shard -> ICI
    all-gather) TrainStep vs the naive flat per-axis psum baseline, with
    BITWISE loss parity asserted every step, the per-link hop-plan table
    emitted, and `multislice_dcn_bytes_per_step` measured from the
    declared plan (== bucket_bytes / ici_size; the flat plan's DCN bytes
    are the full bucket and comm_check C004 flags it). Chipless by
    design: the next chip round is a flag flip on a real 2-slice mesh."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.analysis import comm_check
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.distributed.multislice import (HierarchicalGradReducer,
                                                   SliceTopology)
    from paddle_tpu.distributed.topology import set_hybrid_mesh
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    n_dev = jax.device_count()
    if n_dev < 4:
        print(json.dumps({
            "metric": "multislice_dcn_bytes_per_step", "value": 0.0,
            "unit": "bytes",
            "extra": {"skipped": "needs >=4 devices for the 2-slice mesh",
                      "devices": n_dev}}), flush=True)
        return
    dp = 4 if n_dev >= 8 else n_dev // 2
    topo = SliceTopology(2, dp=dp)
    hidden = 64 if small else 128
    steps = 3 if small else 5
    cfg = GPTConfig(vocab_size=128, hidden_size=hidden, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash_attention=False)

    def loss_fn(m, p, b):
        ids, labels = b
        return functional_call(m, p, ids, labels, training=True)

    rng = np.random.default_rng(0)
    batches = [(jnp.asarray(rng.integers(0, 128, (2 * 2 * dp, 32)),
                            jnp.int32),) * 2 for _ in range(steps)]

    prev = _flags.get_flags(["multislice"])
    results = {}
    try:
        for mode in ("flat", "hierarchical"):
            _flags.set_flags({"multislice": mode})
            set_hybrid_mesh(topo.mesh)
            paddle.seed(0)
            ts = make_sharded_train_step(
                GPTForCausalLM(cfg), AdamW(1e-3), loss_fn,
                mesh=topo.mesh, fsdp_axis=None)
            t0 = time.perf_counter()
            losses = [float(ts.step(b)) for b in batches]
            dt = (time.perf_counter() - t0) / steps
            results[mode] = {"losses": losses,
                             "step_ms": round(dt * 1e3, 3),
                             "grads_bytes": sum(
                                 int(v.size) * v.dtype.itemsize
                                 for v in ts.params.values())}
            set_hybrid_mesh(None)
    finally:
        _flags.set_flags(prev)
        set_hybrid_mesh(None)

    parity_bitwise = results["flat"]["losses"] == \
        results["hierarchical"]["losses"]
    # the declared hop plans (per link class) + the DCN-bytes metric
    reducer = HierarchicalGradReducer(axis="dp", dcn_axis="slice")
    grads = {f"g{i}": np.zeros((results["hierarchical"]["grads_bytes"]
                                // 4,), np.float32) for i in range(1)}
    rows = []
    for mode in ("hierarchical", "flat"):
        for spec in reducer.hop_plan(grads, topo.ici_size,
                                     topo.num_slices, mode=mode):
            rows.append({
                "mode": mode, "stage": spec.name, "link": spec.link,
                "axis": spec.axis, "hops": spec.hops,
                "payload_mb": round(spec.payload_bytes / 2**20, 4),
                "diagnostics": [d.rule for d in
                                comm_check.check_comm_spec(spec)],
            })
    dcn_bytes = reducer.dcn_bytes_per_step(grads, topo.ici_size,
                                           topo.num_slices)
    flat_dcn = reducer.dcn_bytes_per_step(grads, topo.ici_size,
                                          topo.num_slices, mode="flat")
    c004_on_flat = any("C004" in r["diagnostics"] for r in rows
                      if r["mode"] == "flat")
    c004_on_hier = any("C004" in r["diagnostics"] for r in rows
                      if r["mode"] == "hierarchical")
    print(json.dumps({
        "metric": "multislice_dcn_bytes_per_step", "value": dcn_bytes,
        "unit": "bytes/rank (one direction)",
        "extra": {
            "mesh": {"slice": topo.num_slices, "dp": dp,
                     "ici_size": topo.ici_size},
            "modes": results,
            "parity_bitwise": bool(parity_bitwise),
            "flat_dcn_bytes_per_step": flat_dcn,
            "dcn_reduction_factor": round(flat_dcn / max(dcn_bytes, 1),
                                          2),
            "hop_plan": rows,
            "c004_fires_on_flat": bool(c004_on_flat),
            "c004_silent_on_hierarchical": bool(not c004_on_hier),
            "note": ("CPU-mesh wall times are not DCN-meaningful; the "
                     "plan table and the parity are the chipless "
                     "deliverable" if jax.default_backend() != "tpu"
                     else "device-measured"),
        }}), flush=True)
    assert parity_bitwise, (
        f"multislice parity failure: hierarchical losses "
        f"{results['hierarchical']['losses']} vs flat "
        f"{results['flat']['losses']}")
    assert c004_on_flat and not c004_on_hier, (
        "C004 must fire on the naive flat-over-DCN plan and stay silent "
        "on the hierarchical one")


def _gpt_measure(layers, hidden, heads, seq, batch, steps, remat, vocab):
    """Build + time one GPT train-step config under the anomaly guard.

    Returns (measurement_dict, n_params): guarded min-of-N wall + device
    windows against the compiled-cost roofline floor (_measure_guarded)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import functional_call, get_params
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=seq,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    recompute=remat)
    model = GPTForCausalLM(cfg)
    model.train()
    # AMP O2: bf16 params/compute, fp32 master weights in the optimizer.
    model.astype(paddle.bfloat16)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01, multi_precision=True)

    params = get_params(model)
    n_params = sum(int(np.prod(p.shape)) for p in params.values())
    opt_state = opt.init(params)

    def loss_fn(p, ids, labels):
        return functional_call(model, p, ids, labels, training=True)

    def one_step(state, ids, labels):
        p, st = state
        loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels)
        new_p, new_st = opt.apply_gradients(p, grads, st, 1e-4)
        return loss, (new_p, new_st)

    # (a lax.scan over steps — one dispatch — was tried to hide the axon
    # tunnel's ~10 ms/dispatch host latency, but XLA double-buffers the
    # multi-GB carry at L=12, costing far more than it saves)
    step = functools.partial(jax.jit, donate_argnums=(0,))(one_step)

    batches = _gpt_batches(batch, seq, vocab)
    state = (params, opt_state)
    dev = jax.devices()[0]
    flops, nbytes = _compiled_cost(step, state, *batches[0])
    roof = _roofline_for(dev, flops, nbytes)
    m = _measure_guarded(step, state, batches[0], steps, roof,
                         args_seq=batches)
    m.pop("state")
    return m, n_params


def _gpt_batches(batch, seq, vocab, pool=16):
    """A pool of DISTINCT synthetic (ids, labels) batches, cycled one per
    step by the guarded measurement — the reported loss then reflects real
    optimization across batches, not memorization of a single batch
    (VERDICT r5 weak #3: loss_at_l6 = 0.027 after 10 same-batch steps)."""
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    out = []
    for _ in range(pool):
        ids = rng.integers(0, vocab, (batch, seq))
        out.append((jnp.asarray(ids, jnp.int32),
                    jnp.asarray(np.roll(ids, -1, axis=1), jnp.int32)))
    return out


def _gpt_flops_per_token(n_params, layers, seq, hidden):
    # Model FLOPs per token: 6N (fwd+bwd matmuls) + causal attention
    # 12*L*seq*hidden/2 (QK^T + PV, fwd+bwd, halved by causal masking).
    return 6 * n_params + 6 * layers * seq * hidden


def _gpt_13b_measured_path(mode, layers, hidden, heads, seq, vocab,
                           steps=3, budget_gb=None):
    """One REAL full-depth fwd+bwd+update GPT step (ISSUE r6 tentpole).

    mode "sgd_no_moment": SGD(multi_precision) — no moments, everything
    resident (~6 B/param): the zero-transfer baseline that fits HBM.
    mode "adam_offload_moments": the BASELINE-faithful AdamW, its 8 B/param
    of moments parked in pinned host memory and streamed through HBM per
    block by framework/offload.StreamingUpdate — full-depth Adam on one
    chip, which 14 B/param resident cannot do.

    Batch is the largest of (4, 2, 1) whose tools/hbm_budget plan fits;
    the plan rides along in the result. Returns (measurement, n_params,
    batch, plan).
    """
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.framework import offload
    from paddle_tpu.framework.functional import functional_call, get_params
    from paddle_tpu.optimizer import SGD, AdamW
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM
    from tools import hbm_budget

    resident = mode == "sgd_no_moment"
    kwargs = dict(layers=layers, hidden=hidden, heads=heads, seq=seq,
                  vocab=vocab, optimizer="sgd" if resident else "adamw",
                  offload="off" if resident else "moments", remat=True)
    if budget_gb is not None:
        kwargs["budget_gb"] = budget_gb
    batch, plan = hbm_budget.choose_batch(**kwargs)
    if batch is None:
        raise RuntimeError(f"no batch in (4,2,1) fits HBM: {plan}")

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=seq,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    recompute=True)
    model = GPTForCausalLM(cfg)
    model.train()
    model.astype(paddle.bfloat16)
    params = get_params(model)
    n_params = sum(int(np.prod(p.shape)) for p in params.values())

    def loss_fn(p, ids, labels):
        return functional_call(model, p, ids, labels, training=True)

    dev = jax.devices()[0]
    if resident:
        opt = SGD(learning_rate=1e-4, multi_precision=True)
        state = (params, opt.init(params))

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(st, ids, labels):
            p, s = st
            loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels)
            return loss, opt.apply_gradients(p, grads, s, 1e-4)

        batches = _gpt_batches(batch, seq, vocab, pool=8)
        flops, nbytes = _compiled_cost(step, state, *batches[0])
    else:
        opt = AdamW(learning_rate=1e-4, weight_decay=0.01,
                    multi_precision=True)
        stream = offload.StreamingUpdate(opt)
        # moments are born host-side param-by-param — the full 10.5 GB
        # moment set never exists in HBM (offload.init_state)
        state = (params, stream.init_state(params))
        grad_fn = jax.jit(jax.value_and_grad(loss_fn))

        def step(st, ids, labels):
            p, s = st
            loss, grads = grad_fn(p, ids, labels)
            return loss, stream.update(p, grads, s, 1e-4)

        batches = _gpt_batches(batch, seq, vocab, pool=8)
        # roofline floor from the grad program only — a valid lower bound
        # (the streamed update adds compute + host-link time on top)
        flops, nbytes = _compiled_cost(grad_fn, params, *batches[0])
    roof = _roofline_for(dev, flops, nbytes)
    m = _measure_guarded(step, state, batches[0], steps, roof,
                         args_seq=batches)
    m.pop("state")
    return m, n_params, batch, plan


def bench_fault(small: bool):
    """Fault-tolerance goodput, measured (ISSUE 7 / ROADMAP item 5): run
    the elastic kill-and-resume drill (tools/fault_drill.py machinery —
    SIGKILL mid-step AND mid-checkpoint-write, relaunch, resume from
    latest_complete) and emit goodput = useful_step_time /
    wall_time_including_restart plus restart count, lost steps, and
    checkpoint save/restore durations. Bitwise loss parity vs the
    uninterrupted reference is asserted as part of the record — a bench
    number from a run that did NOT recover exactly would be meaningless."""
    import tempfile

    from paddle_tpu.fault import drill

    def _pm_summary(rep):
        pm = rep.get("postmortem") or {}
        pc = pm.get("plan_check") or {}
        return {
            "ok": pm.get("ok"), "coherent": pm.get("coherent"),
            "recorder_files": pm.get("recorder_files"),
            "last_committed_steps": pm.get("last_committed_steps"),
            "deaths": [(d["kind"], d["step"])
                       for d in pm.get("deaths", [])],
            "plan_matches": pc.get("matches"),
            "kill_order_ok": pc.get("kill_order_ok"),
        }

    def _pm_timeline(drill_name, rep):
        # machine-readable postmortem + live-fleet records per drill
        # run, riding the shared timeline JSONL like the serving/health
        # records do
        out_path = os.environ.get("BENCH_TRACE_OUT",
                                  "BENCH_timeline.jsonl")
        try:
            with open(out_path, "a") as f:
                f.write(json.dumps({"kind": "postmortem",
                                    "drill": drill_name,
                                    **_pm_summary(rep)}) + "\n")
                fl = rep.get("fleet")
                if fl:
                    f.write(json.dumps({
                        "kind": "fleet_live", "drill": drill_name,
                        **{k: fl.get(k) for k in (
                            "workers", "incarnations_seen",
                            "silent_incarnations", "final_status",
                            "final_step", "ok")}}) + "\n")
        except OSError:
            pass

    cfg = drill.quick_config()
    if not small:
        cfg.update(total_steps=16, ckpt_every=4)
    workdir = tempfile.mkdtemp(prefix="bench_fault_")
    report = drill.run_drill(workdir, **cfg)
    g = report.get("goodput_record", {})
    parity = report.get("parity", {})
    if report.get("rc") != 0 or "goodput" not in g:
        raise RuntimeError(f"fault drill failed: rc={report.get('rc')} "
                           f"{report.get('error', '')}")
    _emit("fault_tolerance_goodput_pct", g["goodput"] * 100.0,
          "pct useful-step/wall", 0.0,
          {"goodput": g["goodput"],
           "restarts": g["restarts"],
           "lost_steps": g["lost_steps"],
           "useful_step_s": g["useful_step_s"],
           "wall_s": g["wall_s"],
           "ckpt_save_ms": g["ckpt_save"],
           "ckpt_restore_ms": g["ckpt_restore"],
           "steps": cfg["total_steps"],
           "plan": report["plan"]["events"],
           "fired": report.get("fired_events"),
           "parity_bitwise": parity.get("bitwise_equal"),
           "postmortem": _pm_summary(report),
           "method": ("subprocess elastic drill on the CPU mesh: "
                      "deterministic FaultPlan kills the trainer mid-step "
                      "and mid-checkpoint-write; ElasticManager "
                      "relaunches; resume from latest_complete(); wall "
                      "time includes process startup, recompile, restore "
                      "and re-executed steps")})
    if not parity.get("bitwise_equal"):
        raise RuntimeError(f"fault drill parity broken: {parity}")
    _pm_timeline("fault", report)
    if report.get("postmortem") and not report["postmortem"]["ok"]:
        raise RuntimeError(
            f"fault drill postmortem incoherent: "
            f"{report['postmortem']['coherence']} "
            f"plan_check={report['postmortem']['plan_check']}")

    # -- the training-health leg: the chained --health drill (2 kills +
    # inject_nan + inject_hang over the guarded trainer) measured the
    # same way — detection latency in steps and the goodput of a run
    # that detected, rewound, skipped and still matched bitwise
    hcfg = drill.quick_health_config()
    hworkdir = tempfile.mkdtemp(prefix="bench_health_")
    hreport = drill.run_drill(hworkdir, **hcfg)
    hg = hreport.get("goodput_record", {})
    hparity = hreport.get("parity", {})
    hh = hreport.get("health", {})
    if hreport.get("rc") != 0 or "goodput" not in hg:
        raise RuntimeError(
            f"health drill failed: rc={hreport.get('rc')} "
            f"{hreport.get('error', '')}")
    latency = hg.get("detection_latency_steps", {})
    _emit("health_detection_latency_steps", float(latency.get("max", 0)),
          "steps (max over anomalies)", 0.0,
          {"latencies": hh.get("detection_latency_steps"),
           "anomalies": [
               {k: a.get(k) for k in ("kind", "step", "latency_steps")}
               for a in hh.get("anomalies", [])],
           "plan": hreport["plan"]["events"],
           "parity_bitwise": hparity.get("bitwise_equal")})
    _emit("health_recovery_goodput_pct", hg["goodput"] * 100.0,
          "pct useful-step/wall", 0.0,
          {"goodput": hg["goodput"],
           "restarts": hg["restarts"],
           "lost_steps": hg["lost_steps"],
           "rewound_steps": hg["rewound_steps"],
           "skipped_batches": hg["skipped_batches"],
           "parity_bitwise": hparity.get("bitwise_equal"),
           "postmortem": _pm_summary(hreport),
           "method": ("tools/fault_drill.py --quick --health machinery: "
                      "guarded trainer (fused sentinel, hang watchdog, "
                      "SDC canary, Guardian rewind-and-skip) under 2 "
                      "SIGKILLs + 1 injected NaN + 1 injected hang; "
                      "parity vs a clean run handed the same "
                      "poisoned-batch skip set")})
    if not hparity.get("bitwise_equal"):
        raise RuntimeError(f"health drill parity broken: {hparity}")
    _pm_timeline("health", hreport)
    if hreport.get("postmortem") and not hreport["postmortem"]["ok"]:
        raise RuntimeError(
            f"health drill postmortem incoherent: "
            f"{hreport['postmortem']['coherence']} "
            f"plan_check={hreport['postmortem']['plan_check']}")
    # the health records ride the shared timeline JSONL like the serving
    # request records do
    out_path = os.environ.get("BENCH_TRACE_OUT", "BENCH_timeline.jsonl")
    try:
        with open(out_path, "a") as f:
            f.write(json.dumps({
                "kind": "health_drill",
                "detection_latency_steps_max": latency.get("max", 0),
                "recovery_goodput": hg["goodput"],
                "restarts": hg["restarts"],
                "rewound_steps": hg["rewound_steps"],
                "skipped_batches": hg["skipped_batches"],
                "anomaly_kinds": [a.get("kind")
                                  for a in hh.get("anomalies", [])],
                "parity_bitwise": hparity.get("bitwise_equal"),
            }) + "\n")
    except OSError:
        pass


# ---------------------------------------------------------------------------
# BENCH_SERVE: serving engine — continuous batching vs one-shot predictor
# ---------------------------------------------------------------------------

def _serve_trace(n_req, vocab, lo, hi, max_new, seed=0):
    from paddle_tpu.serving import Request
    rng = np.random.default_rng(seed)
    return [Request(rid=f"r{i}",
                    prompt_ids=rng.integers(
                        0, vocab, int(rng.integers(lo, hi + 1))
                    ).astype(np.int32),
                    max_new_tokens=max_new)
            for i in range(n_req)]


def bench_serve(small: bool):
    """Serving tier (ISSUE 8 / ROADMAP item 1): measured tokens/s and
    exact p50/p99 request latency for the paged-KV continuous-batching
    engine over a concurrent ragged-request trace, A/B'd against the
    sequential one-shot ``Predictor.run`` baseline — the seed inference
    tier's serving story: one request at a time, a full forward over the
    growing context per token, no KV reuse (its compile count is held to
    the bucket ladder by the new symbolic-dim padding). Per-request
    outputs are anchored against ``model.generate`` (greedy); the
    compile-budget gate asserts <= n_buckets executable signatures with
    the O001 sentinel silent on BOTH paths. The concurrency sweep rises
    from max_batch=1 (sequential, still KV-cached) to the headline
    width — the continuous-batching win curve."""
    import tempfile

    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.observability import request_timeline
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models.gpt import GPTForCausalLM, gpt_tiny

    e = os.environ.get
    n_req = int(e("BENCH_SERVE_REQUESTS", 6 if small else 12))
    max_new = int(e("BENCH_SERVE_MAX_NEW", 6 if small else 10))
    layers = int(e("BENCH_SERVE_LAYERS", 2 if small else 3))
    hidden = int(e("BENCH_SERVE_HIDDEN", 96 if small else 192))
    heads = int(e("BENCH_SERVE_HEADS", 4 if small else 6))
    vocab = int(e("BENCH_SERVE_VOCAB", 384 if small else 512))
    lo, hi, max_pos = 4, 40, 128
    paddle.seed(0)
    model = GPTForCausalLM(gpt_tiny(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_heads=heads, max_position_embeddings=max_pos))
    model.eval()
    trace = _serve_trace(n_req, vocab, lo, hi, max_new)
    total_new = sum(r.max_new_tokens for r in trace)

    # correctness anchor: greedy generate with the dense per-request cache
    refs = {r.rid: np.asarray(model.generate(
        jnp.asarray(r.prompt_ids[None]),
        max_new_tokens=r.max_new_tokens))[0] for r in trace}

    def run_engine(max_batch):
        eng = ServingEngine(model, block_size=8, num_blocks=96,
                            max_batch=max_batch, max_seq_len=max_pos)
        eng.serve(trace)               # warm pass: pay the bucket compiles
        rt = request_timeline.reset_default()
        t0 = time.perf_counter()
        done = eng.serve(trace)
        wall = time.perf_counter() - t0
        s = rt.summary()
        match = sum(np.array_equal(done[r.rid].output, refs[r.rid])
                    for r in trace) / len(trace)
        return {"max_batch": max_batch,
                "tokens_per_s": round(total_new / wall, 2),
                "wall_s": round(wall, 4),
                "p50_ms": s["p50_ms"], "p99_ms": s["p99_ms"],
                "ttft_p50_ms": s["ttft_p50_ms"],
                "ttft_p99_ms": s["ttft_p99_ms"],
                "preemptions": s["preemptions"],
                "match_fraction": round(match, 4)}, eng

    widths = [1, 2, 4] if small else [1, 2, 4, 8]
    sweep = []
    eng = None
    for w in widths:
        point, eng = run_engine(w)
        sweep.append(point)
    headline = sweep[-1]
    creport = eng.compile_report()
    # measured run's per-request phase records ride the shared timeline
    out_path = os.environ.get("BENCH_TRACE_OUT", "BENCH_timeline.jsonl")
    try:
        request_timeline.current().export_jsonl(out_path, append=True)
    except OSError:
        pass

    # sequential one-shot baseline (the seed predictor serving flow)
    workdir = tempfile.mkdtemp(prefix="bench_serve_")
    paddle.jit.save(model, os.path.join(workdir, "gpt"),
                    input_spec=[((1, "s"), "int32")])
    pred = create_predictor(Config(os.path.join(workdir, "gpt")))

    def one_shot(r):
        ids = list(r.prompt_ids)
        for _ in range(r.max_new_tokens):
            logits = pred.run([np.asarray([ids], np.int32)])[0]
            ids.append(int(np.argmax(logits[0, len(ids) - 1])))
        return np.asarray(ids, np.int32)

    for r in trace[:2]:
        one_shot(r)                    # warm the bucket executables
    t0 = time.perf_counter()
    seq_out = {r.rid: one_shot(r) for r in trace}
    seq_wall = time.perf_counter() - t0
    seq_tps = total_new / seq_wall if seq_wall else 0.0
    seq_match = sum(np.array_equal(seq_out[r.rid], refs[r.rid])
                    for r in trace) / len(trace)
    pred_report = pred.bucket_report()

    speedup = headline["tokens_per_s"] / seq_tps if seq_tps else 0.0
    extra = {
        "config": {"layers": layers, "hidden": hidden, "heads": heads,
                   "vocab": vocab, "requests": n_req, "max_new": max_new,
                   "prompt_lens": [int(r.prompt_ids.size) for r in trace]},
        "concurrency_sweep": sweep,
        "p50_ms": headline["p50_ms"], "p99_ms": headline["p99_ms"],
        "ttft_p50_ms": headline["ttft_p50_ms"],
        "ttft_p99_ms": headline["ttft_p99_ms"],
        "sequential_tokens_per_s": round(seq_tps, 2),
        "sequential_wall_s": round(seq_wall, 4),
        "speedup_vs_one_shot": round(speedup, 2),
        "match_fraction": headline["match_fraction"],
        "sequential_match_fraction": round(seq_match, 4),
        "compile_report": creport,
        "predictor_bucket_report": pred_report,
        "method": ("continuous batching (paged KV, bucketed shapes) vs "
                   "the one-shot AOT predictor re-running the full "
                   "forward per token, same ragged trace, greedy; "
                   "engine outputs anchored token-exact against "
                   "model.generate; both paths warmed before timing"),
    }
    _emit("serving_tokens_per_s", headline["tokens_per_s"], "tokens/s",
          0.0, extra)
    _emit("serving_p50_ms", headline["p50_ms"], "ms", 0.0,
          {"max_batch": headline["max_batch"]})
    _emit("serving_p99_ms", headline["p99_ms"], "ms", 0.0,
          {"max_batch": headline["max_batch"]})
    if headline["match_fraction"] < 0.75:
        raise RuntimeError(
            f"serving outputs diverged from model.generate: "
            f"match {headline['match_fraction']}")
    if not creport["within_budget"] or creport["o001_fired"]:
        raise RuntimeError(f"serving compile budget violated: {creport}")
    if pred_report["o001_fired"]:
        raise RuntimeError(
            f"predictor bucket padding failed (O001 fired): {pred_report}")
    if speedup < 2.0:
        raise RuntimeError(
            f"continuous batching speedup {speedup:.2f}x < 2x over the "
            f"sequential one-shot baseline")

    bench_serve_resilience(model, max_pos, vocab, small)
    if os.environ.get("BENCH_SERVE_TIERS", "1") != "0":
        bench_serve_throughput_tiers(small)


def bench_serve_resilience(model, max_pos, vocab, small: bool):
    """Serving resilience (ISSUE 9): the SLO half of BENCH_SERVE.

    Two measured components, emitted as serving_slo_attainment_pct +
    serving_shed_rate:

    - the **subprocess serve drill** (tools/serve_drill.py machinery):
      SIGKILL the serving worker mid-decode and mid-spill, relaunch,
      replay unacknowledged requests from the fsynced journal — zero
      lost, zero duplicated, survivors token-exact vs model.generate;
    - a **fault-injected overload trace** on a deliberately starved
      engine: tight deadlines + mixed priorities, bounded admission
      (max_waiting), the shed policy armed in degrade mode, one request
      that outgrows the pool (validate_capacity=False — it must FAIL
      per-request, never crash the loop), and a SpillError injected
      through the serve.mid_spill seam. SLO attainment = fraction of
      deadline-carrying requests answered in time; shed rate =
      (shed + rejected) / submitted.
    """
    import tempfile

    from paddle_tpu.fault.injection import register_fire_point
    from paddle_tpu.observability import request_timeline
    from paddle_tpu.serving import (Request, ServingEngine, ShedPolicy,
                                    SpillError, Status)
    from paddle_tpu.serving import drill as serve_drill

    # -- (1) the kill-and-replay drill (subprocess pod) ---------------------
    drill_dir = tempfile.mkdtemp(prefix="bench_serve_drill_")
    drill_report = serve_drill.run_serve_drill(drill_dir)
    if not drill_report.get("ok"):
        raise RuntimeError(f"serve drill failed: {drill_report}")
    once = drill_report["exactly_once"]
    fl = drill_report.get("fleet") or {}
    try:
        with open(os.environ.get("BENCH_TRACE_OUT",
                                 "BENCH_timeline.jsonl"), "a") as f:
            f.write(json.dumps({
                "kind": "fleet_live", "drill": "serve",
                **{k: fl.get(k) for k in (
                    "workers", "incarnations_seen",
                    "silent_incarnations", "final_status",
                    "live_goodput", "postmortem_goodput",
                    "goodput_match", "ok")}}) + "\n")
    except OSError:
        pass

    # -- (2) fault-injected overload trace ----------------------------------
    # The pool hog goes FIRST (closed-loop serve submits in order, so it
    # lands inside the bounded queue): its 120-token prompt takes all 15
    # usable blocks at admission and its first decode token needs a 16th
    # -> it must FAIL per-request (OutOfBlocks isolated), never a crash.
    rng = np.random.default_rng(11)
    n_over = 10 if small else 16
    trace = [Request(rid="hog", prompt_ids=rng.integers(0, vocab, 120),
                     max_new_tokens=8, deadline_s=120.0, priority=2)]
    for i in range(n_over):
        plen = int(rng.integers(16, 33))
        # a third of the trace gets an unattainable deadline (guaranteed
        # expiry), the rest a generous one; priorities split the classes;
        # 2-4 prompt blocks + 2 blocks of growth x 4-wide overcommits the
        # 15-block pool, so the LIFO preemption/spill path runs hot
        tight = i % 3 == 2
        trace.append(Request(
            rid=f"ov{i}", prompt_ids=rng.integers(0, vocab, plen),
            max_new_tokens=16, deadline_s=0.001 if tight else 120.0,
            priority=0 if tight else 1))

    rt = request_timeline.reset_default()
    eng = ServingEngine(
        model, block_size=8, num_blocks=16, max_batch=4,
        max_seq_len=max_pos, max_waiting=8,
        shed_policy=ShedPolicy(min_free_block_frac=0.2,
                               max_p99_decode_ms=5e3, degrade=True),
        validate_capacity=False)
    state = {"spills": 0}

    def spill_bomb():  # the in-process fault: first spill's host commit dies
        state["spills"] += 1
        if state["spills"] == 1:
            raise SpillError("injected host allocation failure "
                             "(BENCH_SERVE resilience leg)")

    register_fire_point("serve.mid_spill", spill_bomb)
    try:
        results = eng.serve(trace)
    finally:
        register_fire_point("serve.mid_spill", None)

    # the engine degraded instead of dying: loop drained, zero leaks
    eng.sched.assert_idle()
    if eng.cache.allocator.n_used != 0:
        raise RuntimeError(
            f"overload trace leaked {eng.cache.allocator.n_used} KV blocks")
    hog = results["hog"]
    if getattr(hog, "status", None) is not Status.FAILED:
        raise RuntimeError(
            "pool-exhaustion request was expected to FAIL per-request "
            f"(engine survival proof), got {hog!r}")

    s = rt.summary()
    slo = s["slo_attainment_pct"]
    if slo is None:
        raise RuntimeError(f"no deadline-carrying records: {s}")
    extra = {
        "outcomes": s["outcomes"],
        "requests": len(trace),
        "served": s["served"],
        "deadline_expired": s["outcomes"].get("expired", 0),
        "engine_mode_final": eng.mode,
        "injected_spill_fault": True,
        "pool_exhaustion_isolated": True,
        "drill": {
            "wall_s": drill_report["wall_s"],
            "fired_events": drill_report["fired_events"],
            "restarts": drill_report["restarts"],
            "lost": once["lost"], "duplicated": once["duplicated"],
            "token_exact": drill_report["token_exact"],
            "served": drill_report["served"],
        },
        "method": ("fault-injected overload trace on a starved engine "
                   "(16-block pool, max_waiting=8, shed policy armed in "
                   "degrade mode, SpillError injected at the first host "
                   "spill, one request outgrowing the pool) + the "
                   "subprocess serve drill (SIGKILL mid-decode and "
                   "mid-spill, exactly-once journal replay, token-exact "
                   "survivors)"),
    }
    _emit("serving_slo_attainment_pct", slo, "pct requests in deadline",
          0.0, extra)
    _emit("serving_shed_rate", s["shed_rate"],
          "shed+rejected / submitted", 0.0,
          {"outcomes": s["outcomes"], "max_waiting": 8,
           "shed_policy": repr(eng.shed_policy)})


def bench_serve_throughput_tiers(small: bool):
    """Serving throughput rung 2 (ISSUE 13): the three flag-gated tiers
    measured on a compute-dominant CPU-mesh config (prompts long enough
    that prefill FLOPs, not dispatch latency, carry the comparison):

    - **prefix leg** — a shared-system-prompt workload replayed at share
      ratios 0/0.5/0.8 through the engine with and without the radix
      tree: the prefix-hit-rate x tokens/s curve, with tokens/s >= 1.5x
      and peak live blocks (cache-idle tree holds excluded — they evict
      on demand) reduced >= 2x GATED at the 80% ratio;
    - **chunked leg** — residents decoding while a long prompt arrives:
      max step wall (the resident-visible stall) with the chunked
      budget must undercut the one-shot arm's unbounded stall;
    - **speculative leg** — a decode-heavy trace swept over gamma with
      the NGram drafter, greedy accept-prefix verify in one bucketed
      extend dispatch: best-arm speedup >= 1.0x GATED, accept stats
      recorded and the measured-winner gamma persisted into the
      autotune cache (``FLAGS_serve_speculative=-1`` reads it back);
      the record also lands in BENCH_timeline.jsonl.

    Every arm's outputs are asserted token-exact against
    ``model.generate`` — a throughput number never describes drifted
    tokens."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.serving import Request, ServingEngine
    from paddle_tpu.serving.speculative import store_gamma
    from paddle_tpu.text.models.gpt import GPTForCausalLM, gpt_tiny

    e = os.environ.get
    vocab = int(e("BENCH_SERVE_TIERS_VOCAB", 512))
    hidden = int(e("BENCH_SERVE_TIERS_HIDDEN", 192))
    layers = int(e("BENCH_SERVE_TIERS_LAYERS", 3))
    max_pos = 256
    bs_, nb, mb = 16, 96, 4
    n_users = 6 if small else 8
    paddle.seed(0)
    model = GPTForCausalLM(gpt_tiny(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_heads=6, max_position_embeddings=max_pos))
    model.eval()

    def check_exact(results, trace):
        bad = [r.rid for r in trace if not np.array_equal(
            results[r.rid].output,
            np.asarray(model.generate(jnp.asarray(r.prompt_ids[None]),
                                      max_new_tokens=r.max_new_tokens))[0])]
        if bad:
            raise RuntimeError(f"tier outputs diverged from "
                               f"model.generate: {bad}")

    # -- (1) prefix leg: hit-rate x tokens/s curve ---------------------------
    plen, max_new = 224, 3

    def prefix_trace(ratio, shift):
        rng = np.random.default_rng(17)
        sl = int(round(ratio * plen / bs_)) * bs_
        shared = (rng.integers(0, vocab, sl) + shift) % vocab
        return [Request(
            rid=f"u{i}s{shift}",
            prompt_ids=np.concatenate([
                shared,
                (rng.integers(0, vocab, max(1, plen - sl)) + shift)
                % vocab]).astype(np.int32),
            max_new_tokens=max_new) for i in range(n_users)]

    def prefix_arm(ratio, on):
        eng = ServingEngine(model, block_size=bs_, num_blocks=nb,
                            max_batch=mb, max_seq_len=max_pos,
                            prefix_cache=on)
        # two distinct-token warm passes: every bucket/width signature
        # compiles outside the timed window while the timed trace still
        # shares only among itself
        eng.serve(prefix_trace(ratio, 7))
        eng.serve(prefix_trace(ratio, 29))
        eng.reset_peaks()
        trace = prefix_trace(ratio, 0)
        t0 = time.perf_counter()
        results = eng.serve(trace)
        wall = time.perf_counter() - t0
        check_exact(results, trace)
        tps = sum(r.max_new_tokens for r in trace) / wall
        return tps, eng

    curve = []
    for ratio in (0.0, 0.5, 0.8):
        tps_off, eng_off = prefix_arm(ratio, False)
        tps_on, eng_on = prefix_arm(ratio, True)
        rep = eng_on.prefix_report()
        curve.append({
            "share_ratio": ratio,
            "prefix_hit_rate": rep["hit_rate"],
            "tokens_per_s_off": round(tps_off, 1),
            "tokens_per_s_on": round(tps_on, 1),
            "speedup": round(tps_on / tps_off, 3),
            "peak_live_blocks_off": eng_off.peak_live_blocks,
            "peak_live_blocks_on": eng_on.peak_live_blocks,
            "blocks_reduction": round(
                eng_off.peak_live_blocks
                / max(eng_on.peak_live_blocks, 1), 3),
        })
    head = curve[-1]                 # the 80%-share production point
    _emit("serving_prefix_tokens_per_s", head["tokens_per_s_on"],
          "tokens/s @ 80% share", 0.0, {
              "curve": curve,
              "speedup_at_80": head["speedup"],
              "blocks_reduction_at_80": head["blocks_reduction"],
              "config": {"prompt_len": plen, "max_new": max_new,
                         "users": n_users, "hidden": hidden,
                         "layers": layers, "block_size": bs_},
              "method": ("shared-system-prompt trace (tools/serve_bench"
                         ".py --prefix-trace shape) at share ratios "
                         "0/0.5/0.8, radix-tree arm vs private-KV arm, "
                         "two distinct-token warm passes, outputs "
                         "token-exact; peak live blocks exclude "
                         "evictable cache-idle tree holds")})
    if head["speedup"] < 1.5:
        raise RuntimeError(
            f"prefix-cache tokens/s {head['speedup']}x < 1.5x at 80% "
            f"share: {curve}")
    if head["blocks_reduction"] < 2.0:
        raise RuntimeError(
            f"prefix-cache peak live blocks reduced only "
            f"{head['blocks_reduction']}x < 2x: {curve}")

    # -- (2) chunked-prefill leg: bounded stall ------------------------------
    rng = np.random.default_rng(5)

    def chunk_arm(chunk):
        eng = ServingEngine(model, block_size=bs_, num_blocks=nb,
                            max_batch=mb, max_seq_len=max_pos,
                            chunked_prefill=chunk)
        mk = lambda rid, n, new: Request(  # noqa: E731
            rid=rid, prompt_ids=rng.integers(0, vocab, n).astype(np.int32),
            max_new_tokens=new)
        warm = [mk(f"w{i}", 16, 24) for i in range(3)] + \
            [mk("wl", 224, 2)]
        eng.serve(warm)
        residents = [mk(f"d{i}", 16, 24) for i in range(3)]
        long_req = mk("long", 224, 2)
        for r in residents:
            eng.submit(r)
        steps_ms, results = [], {}
        for it in range(200):
            t0 = time.perf_counter()
            done = eng.step()
            steps_ms.append((time.perf_counter() - t0) * 1e3)
            for s in done:
                results[s.rid] = s
            if it == 5:
                eng.submit(long_req)
            if not eng.sched.n_pending:
                break
        check_exact(results, residents + [long_req])
        tail = steps_ms[6:]
        return (max(tail),
                sorted(tail)[int(0.99 * (len(tail) - 1))])

    stall_off, p99_off = chunk_arm(0)
    stall_on, p99_on = chunk_arm(32)
    _emit("serving_chunked_prefill_stall_ms", stall_on, "ms max step "
          "wall during long-prompt arrival", 0.0, {
              "unchunked_stall_ms": round(stall_off, 2),
              "chunked_stall_ms": round(stall_on, 2),
              "p99_step_ms_unchunked": round(p99_off, 2),
              "p99_step_ms_chunked": round(p99_on, 2),
              "stall_reduction": round(stall_off / stall_on, 2),
              "chunk_tokens": 32, "long_prompt": 224,
              "method": ("3 short residents decoding, a 224-token "
                         "prompt arrives at iteration 5; max/p99 "
                         "engine-step wall over the remaining "
                         "iterations = the resident-visible stall; "
                         "chunked budget 32 tokens/iteration vs the "
                         "one-shot prefill")})
    if stall_on >= stall_off:
        raise RuntimeError(
            f"chunked prefill did not bound the long-prompt stall: "
            f"chunked {stall_on:.1f}ms >= one-shot {stall_off:.1f}ms")

    # -- (3) speculative leg: gamma sweep ------------------------------------
    def spec_trace():
        r = np.random.default_rng(9)
        return [Request(rid=f"s{i}",
                        prompt_ids=r.integers(
                            0, vocab, int(r.integers(8, 17))).astype(
                                np.int32),
                        max_new_tokens=24) for i in range(n_users)]

    def spec_arm(gamma):
        eng = ServingEngine(model, block_size=bs_, num_blocks=nb,
                            max_batch=mb, max_seq_len=max_pos,
                            speculative=gamma)
        tr = spec_trace()
        eng.serve(tr)        # identical warm: same widths, no tree
        t0 = time.perf_counter()
        results = eng.serve(tr)
        wall = time.perf_counter() - t0
        check_exact(results, tr)
        return sum(r.max_new_tokens for r in tr) / wall, eng

    tps_base, _ = spec_arm(0)
    arms = []
    for g in (2, 4, 6):
        tps_g, eng_g = spec_arm(g)
        r = eng_g.spec_report()
        arms.append({"gamma": g, "tokens_per_s": round(tps_g, 1),
                     "speedup": round(tps_g / tps_base, 3),
                     "accept_rate": r["accept_rate"],
                     "mean_accept_len": r["mean_accept_len"],
                     "tokens_per_verify": r["tokens_per_verify"]})
    best = max(arms, key=lambda a: a["tokens_per_s"])
    t_desc = f"gpt_l{layers}_h{hidden}_v{vocab}"
    store_gamma(t_desc, "ngram", best["gamma"],
                measured_ms=1e3 / max(best["tokens_per_s"], 1e-9))
    _emit("serving_speculative_speedup", best["speedup"],
          "x vs plain decode", 0.0, {
              "baseline_tokens_per_s": round(tps_base, 1),
              "arms": arms, "best_gamma": best["gamma"],
              "spec_accept_rate": best["accept_rate"],
              "drafter": "ngram",
              "method": ("decode-heavy trace (short prompts, 24 new "
                         "tokens), NGram prompt-lookup drafter, greedy "
                         "accept-prefix verify in one bucketed "
                         "decode-gamma extend dispatch; gamma swept "
                         "{2,4,6}, measured winner persisted to the "
                         "autotune cache; outputs token-exact")})
    if best["speedup"] < 1.0:
        raise RuntimeError(
            f"speculative speedup {best['speedup']}x < 1.0x: {arms}")
    out_path = os.environ.get("BENCH_TRACE_OUT", "BENCH_timeline.jsonl")
    try:
        with open(out_path, "a") as f:
            f.write(json.dumps({
                "kind": "spec_decode",
                "spec_accept_rate": best["accept_rate"],
                "mean_accept_len": best["mean_accept_len"],
                "speedup": best["speedup"],
                "gamma": best["gamma"],
                "drafter": "ngram",
                "prefix_curve": curve,
                "chunked_stall_ms": round(stall_on, 2),
                "unchunked_stall_ms": round(stall_off, 2),
            }) + "\n")
    except OSError:
        pass


def bench_gpt_13b():
    """BASELINE config 4, the PRIMARY metric: GPT-3 1.3B tokens/sec/chip.

    Two components, emitted as ONE record:

    - the r3-r5 per-layer extrapolation (measure the exact 1.3B layer
      shape at L=6 and L=12, fit t = a + b*L, report t(24)) — kept for
      continuity and as the cross-check target;
    - ``measured_full_depth`` (NEW, VERDICT r5 missing #1): one real
      24-layer fwd+bwd+update step, device-timed and anomaly-guarded,
      under both the SGD-no-moment resident path and the AdamW
      host-offloaded-moments path (framework/offload.py). The 18.4 GB
      > 15.75 GB capacity wall that forced the extrapolation for two
      rounds is gone — moments live in pinned host memory and stream
      through HBM per block.

    Headline: the measured AdamW number when it produced a clean window
    (the reference's methodology gates on measured runs only); the
    extrapolation is confirmed if within 5%, otherwise marked corrected
    and the MFU restated from the measurement.
    """
    import jax

    if os.environ.get("BENCH_13B_SMOKE") == "1":
        # CPU wiring smoke: tiny dims, same code path end to end
        seq, batch, heads, hidden, vocab = 32, 2, 2, 64, 128
        depths, full_depth, fit_steps, meas_steps = (1, 2), 4, 2, 2
    else:
        seq, batch, heads, hidden, vocab = 2048, 4, 16, 2048, 50304
        depths, full_depth, fit_steps, meas_steps = (6, 12), 24, 8, 3
    pts = []
    for L in depths:
        m, n_params = _gpt_measure(
            L, hidden, heads, seq, batch, steps=fit_steps, remat=True,
            vocab=vocab)
        pts.append((L, m, n_params))
    # headline on DEVICE time when a trace was parsed for BOTH depths (the
    # axon tunnel's ~10-15 ms/dispatch host latency is a harness artifact,
    # not chip throughput); otherwise wall time for both — never mixed
    ms = [p[1] for p in pts]
    # "device" only when BOTH depths produced CLEAN device windows —
    # m["timing"] is set to "device" only in that case (an all-anomalous
    # device trace must never become the headline basis).
    timing_basis = ("device" if all(m["timing"] == "device" for m in ms)
                    else "wall")
    times = [m["device_s" if timing_basis == "device" else "wall_s"]
             for m in ms]
    anomaly = any(m["anomaly"] for m in ms)
    (l1, l2), (t1, t2) = (pts[0][0], pts[1][0]), times
    per_layer = (t2 - t1) / (l2 - l1)
    fixed = t1 - l1 * per_layer
    t24 = fixed + full_depth * per_layer
    # param count of the true 24-layer model (trunk scales linearly; embed
    # + position table are the fixed part)
    n6 = pts[0][2]
    per_layer_params = (pts[1][2] - n6) / (l2 - l1)
    n24 = int(n6 + (full_depth - l1) * per_layer_params)
    extrap_tok_s = batch * seq / t24
    flops_per_token = _gpt_flops_per_token(n24, full_depth, seq, hidden)
    peak = _peak_flops(jax.devices()[0])
    extrap_mfu = extrap_tok_s * flops_per_token / peak

    # --- measured full depth, both paths -----------------------------------
    budget_gb = None if os.environ.get("BENCH_13B_SMOKE") != "1" else 1e9
    measured = {}
    for mode in ("sgd_no_moment", "adam_offload_moments"):
        try:
            m, n_meas, mbatch, plan = _gpt_13b_measured_path(
                mode, full_depth, hidden, heads, seq, vocab,
                steps=meas_steps, budget_gb=budget_gb)
            tok_s = mbatch * seq / m["used_s"]
            measured[mode] = {
                "tokens_per_sec": round(tok_s, 1),
                "mfu": round(tok_s * flops_per_token / peak, 4),
                "step_ms": round(m["used_s"] * 1e3, 2),
                "batch": mbatch, "loss": m["loss"],
                "n_params": n_meas,
                "hbm_plan": {"device_gb": plan["device_gb"],
                             "host_gb": plan["host_gb"],
                             "fits": plan["fits"],
                             "rows_gb": plan["rows_gb"]},
                **_guard_extra(m),
            }
        except Exception as e:  # OOM/compile failure must not kill primary
            measured[mode] = {"error": f"{type(e).__name__}: {e}"[:400]}

    adam = measured.get("adam_offload_moments", {})
    adam_ok = "tokens_per_sec" in adam and not adam.get("anomaly")
    if adam_ok:
        agree_pct = 100.0 * (adam["tokens_per_sec"] / extrap_tok_s - 1.0)
        confirmed = abs(agree_pct) <= 5.0
        headline_tok_s, headline_mfu = adam["tokens_per_sec"], adam["mfu"]
        method = ("measured_full_depth: real %d-layer fwd+bwd+update, "
                  "AdamW moments in pinned host memory streamed per block "
                  "(FLAGS_offload_optimizer=moments); extrapolation %s "
                  "(%.1f%% apart)" % (
                      full_depth,
                      "confirmed within 5%" if confirmed
                      else "CORRECTED — headline restated from measurement",
                      agree_pct))
    else:
        agree_pct, confirmed = None, None
        headline_tok_s, headline_mfu = extrap_tok_s, extrap_mfu
        method = ("per-layer extrapolation (measured full-depth run "
                  "unavailable this round — see measured_full_depth for "
                  "the failure record)")

    _emit("gpt3_1p3b_train_tokens_per_sec_per_chip", headline_tok_s,
          "tokens/sec/chip", headline_mfu,
          {"n_params": n24, "loss_at_l6": ms[0]["loss"],
           "anomaly": anomaly if not adam_ok else bool(adam.get("anomaly")),
           "config": {"layers": full_depth, "hidden": hidden,
                      "heads": heads, "seq": seq, "batch": batch,
                      "remat": True, "amp": "O2 (bf16 + f32 master)"},
           "method": method,
           "measured_full_depth": measured,
           "extrapolation": {
               "tokens_per_sec": round(extrap_tok_s, 1),
               "mfu": round(extrap_mfu, 4),
               "step_ms": round(t24 * 1e3, 2),
               "per_layer_ms": round(per_layer * 1e3, 2),
               "fixed_ms": round(fixed * 1e3, 2),
               "agreement_pct": (round(agree_pct, 2)
                                 if agree_pct is not None else None),
               "confirmed_within_5pct": confirmed,
               "anomaly": anomaly,
           },
           "measured_points": [
               {"layers": l, "step_ms": round(t * 1e3, 2),
                "wall_step_ms": round(m["wall_s"] * 1e3, 2)
                if m["wall_s"] else None,
                "anomaly": m["anomaly"],
                "windows": m["windows"], "discarded": m["discarded"],
                "roofline_ms": m["roofline_ms"]}
               for (l, m, _), t in zip(pts, times)],
           "timing": ("device (xprof hlo_stats; wall incl. ~10-15 ms/step "
                      "axon-tunnel dispatch latency reported alongside)"
                      if timing_basis == "device" else "wall"),
           "step_ms": (adam["step_ms"] if adam_ok
                       else round(t24 * 1e3, 2)),
           "baseline_config": 4})


def bench_gpt(small: bool):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import functional_call, get_params
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    if not small and not os.environ.get("BENCH_LAYERS"):
        # Default full run reports the BASELINE-faithful 1.3B metric:
        # extrapolation + measured full depth (r6 tentpole).
        return bench_gpt_13b()

    # head_dim 128 (not 64) matches the BASELINE GPT-3 1.3B shape
    # (16 heads x 128 at d_model 2048) and fills the 128-lane MXU; batch 16
    # is the measured single-chip sweet spot (batch 32 spills HBM).
    layers = int(os.environ.get("BENCH_LAYERS", 2 if small else 16))
    hidden = int(os.environ.get("BENCH_HIDDEN", 128 if small else 1024))
    heads = int(os.environ.get("BENCH_HEADS", 4 if small else 8))
    seq = int(os.environ.get("BENCH_SEQ", 128 if small else 1024))
    batch = int(os.environ.get("BENCH_BATCH", 2 if small else 16))
    steps = int(os.environ.get("BENCH_STEPS", 2 if small else 10))
    remat = os.environ.get("BENCH_REMAT") == "1"
    vocab = 512 if small else 50304

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=seq,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    recompute=remat)
    model = GPTForCausalLM(cfg)
    model.train()
    # AMP O2: bf16 params/compute, fp32 master weights in the optimizer.
    model.astype(paddle.bfloat16)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01, multi_precision=True)

    params = get_params(model)
    n_params = sum(int(np.prod(p.shape)) for p in params.values())
    opt_state = opt.init(params)

    def loss_fn(p, ids, labels):
        return functional_call(model, p, ids, labels, training=True)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, ids, labels):
        p, st = state
        loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels)
        new_p, new_st = opt.apply_gradients(p, grads, st, 1e-4)
        return loss, (new_p, new_st)

    batches = _gpt_batches(batch, seq, vocab)
    dev = jax.devices()[0]
    flops, nbytes = _compiled_cost(step, (params, opt_state), *batches[0])
    roof = _roofline_for(dev, flops, nbytes)
    m = _measure_guarded(step, (params, opt_state), batches[0], steps,
                         roof, args_seq=batches)
    dt = m["used_s"]
    tokens_per_sec = batch * seq / dt
    # Model FLOPs per token: 6N (fwd+bwd matmuls) + causal attention
    # 12*L*seq*hidden/2 (QK^T + PV, fwd+bwd, halved by causal masking).
    flops_per_token = 6 * n_params + 6 * layers * seq * hidden
    mfu = tokens_per_sec * flops_per_token / _peak_flops(dev)
    _emit(f"gpt_{n_params/1e6:.0f}M_train_tokens_per_sec_per_chip",
          tokens_per_sec, "tokens/sec/chip", mfu,
          {"loss": m["loss"], "n_params": n_params,
           "config": {"layers": layers, "hidden": hidden, "heads": heads,
                      "seq": seq, "batch": batch, "steps": steps,
                      "remat": remat},
           **_guard_extra(m),
           "step_ms": round(dt * 1e3, 2), "baseline_config": 4})


_SNAPSHOT_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _next_snapshot_n(root):
    """NN for this run's ``BENCH_r<NN>.json``: last COMMITTED snapshot + 1
    (so reruns in a dirty tree overwrite their own snapshot instead of
    walking the counter), falling back to the directory scan when git is
    unavailable."""
    names = []
    try:
        out = subprocess.run(
            ["git", "ls-files", "BENCH_r*.json"], cwd=root,
            capture_output=True, text=True, timeout=30)
        if out.returncode == 0:
            names = out.stdout.split()
    except (OSError, subprocess.SubprocessError):
        pass
    if not names:
        names = [n for n in os.listdir(root) if _SNAPSHOT_RE.search(n)]
    nums = [int(_SNAPSHOT_RE.search(n).group(1)) for n in names
            if _SNAPSHOT_RE.search(n)]
    return max(nums, default=0) + 1


def _write_snapshot(root, stdout_text, rc, cmd):
    """Persist the per-run snapshot (same shape as the committed
    BENCH_r01..r05: n/cmd/rc/tail/parsed) so the trajectory keeps its
    per-run anchors and not just the BENCH_timeline.jsonl stream.
    ``parsed`` is the last metric line — the driver's headline (GPT)."""
    n = _next_snapshot_n(root)
    parsed = None
    for line in reversed(stdout_text.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            parsed = rec
            break
    path = os.path.join(root, "BENCH_r%02d.json" % n)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"n": n, "cmd": cmd, "rc": rc,
                   "tail": stdout_text[-8000:], "parsed": parsed}, f)
        f.write("\n")
    return path


class _TeeStdout:
    """Pass-through stdout capture for the snapshot's ``tail``."""

    def __init__(self, inner):
        self.inner = inner
        self.chunks = []

    def write(self, s):
        self.chunks.append(s)
        return self.inner.write(s)

    def flush(self):
        self.inner.flush()

    def text(self):
        return "".join(self.chunks)


def _main_impl():
    small = os.environ.get("BENCH_SMALL") == "1"
    _prewarm_autotune()
    which = os.environ.get("BENCH_CONFIGS", "all")
    selected = {w.strip() for w in which.split(",")}
    by_name = {"resnet": bench_resnet, "bert": bench_bert,
               "ernie": bench_ernie}
    for name, fn in by_name.items():
        if "all" in selected or name in selected:
            try:
                fn(small)
            except Exception as e:  # secondary configs must not kill the run
                print(json.dumps({"metric": f"{fn.__name__}_FAILED",
                                  "error": str(e)[:500]}), flush=True)
    if os.environ.get("BENCH_PALLAS_CONV") == "1" and (
            "all" in selected or "resnet" in selected):
        try:
            bench_pallas_conv_ab(small)
        except Exception as e:
            print(json.dumps({"metric": "bench_pallas_conv_ab_FAILED",
                              "error": str(e)[:500]}), flush=True)
    # telemetry overhead A/B + this run's timeline export (before the
    # primary so the driver's final-line headline stays the GPT metric)
    if os.environ.get("BENCH_TELEMETRY", "1") != "0":
        try:
            bench_telemetry_overhead(small)
        except Exception as e:
            print(json.dumps({"metric": "bench_telemetry_overhead_FAILED",
                              "error": str(e)[:500]}), flush=True)
        try:
            bench_flight_recorder_overhead(small)
        except Exception as e:
            print(json.dumps(
                {"metric": "bench_flight_recorder_overhead_FAILED",
                 "error": str(e)[:500]}), flush=True)
        try:
            bench_fleet_telemetry_overhead(small)
        except Exception as e:
            print(json.dumps(
                {"metric": "bench_fleet_telemetry_overhead_FAILED",
                 "error": str(e)[:500]}), flush=True)
    # comm-overlap A/B (FLAGS_comm_overlap off vs tp): emits the
    # comm_overlap metric — measured on >=2-device meshes, static hop
    # plans only on a single chip (ready for the next device round)
    if os.environ.get("BENCH_COMM_OVERLAP", "1") != "0":
        try:
            bench_comm_overlap(small)
        except Exception as e:
            print(json.dumps({"metric": "bench_comm_overlap_FAILED",
                              "error": str(e)[:500]}), flush=True)
    # multi-slice tier: 2-slice dryrun (hierarchical vs flat DP reduction,
    # bitwise parity + per-link hop plans + DCN bytes/step — chipless)
    if os.environ.get("BENCH_MULTISLICE", "1") != "0":
        try:
            bench_multislice(small)
        except Exception as e:
            print(json.dumps({"metric": "bench_multislice_FAILED",
                              "error": str(e)[:500]}), flush=True)
    # fault-tolerance drill: kill/relaunch/resume with measured goodput
    # (subprocesses on the CPU mesh — runs chipless, ~30s quick config)
    if os.environ.get("BENCH_FAULT", "1") != "0":
        try:
            bench_fault(small)
        except Exception as e:
            print(json.dumps({"metric": "bench_fault_FAILED",
                              "error": str(e)[:500]}), flush=True)
    # serving engine: continuous batching + paged KV vs the one-shot
    # predictor, measured tokens/s and p50/p99 on a ragged trace (CPU-mesh
    # sized model — runs chipless; the request records join the timeline)
    if os.environ.get("BENCH_SERVE", "1") != "0":
        try:
            bench_serve(small)
        except Exception as e:
            print(json.dumps({"metric": "bench_serve_FAILED",
                              "error": str(e)[:500]}), flush=True)
    if "all" in selected or "gpt" in selected:
        bench_gpt(small)  # primary: printed last


def main():
    if os.environ.get("BENCH_SNAPSHOT", "1") == "0":
        return _main_impl()
    root = os.environ.get("BENCH_SNAPSHOT_DIR",
                          os.path.dirname(os.path.abspath(__file__)))
    tee = _TeeStdout(sys.stdout)
    sys.stdout = tee
    rc = 0
    try:
        _main_impl()
    except BaseException:
        rc = 1
        raise
    finally:
        sys.stdout = tee.inner
        try:
            _write_snapshot(root, tee.text(), rc,
                            "python " + " ".join(sys.argv))
        except OSError as e:
            print(json.dumps({"metric": "bench_snapshot_FAILED",
                              "error": str(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
