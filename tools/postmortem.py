#!/usr/bin/env python
"""Postmortem reconstruction CLI: one fleet story from the black boxes.

    python tools/postmortem.py RUN_DIR                 # human narrative
    python tools/postmortem.py RUN_DIR --json          # machine report
    python tools/postmortem.py RUN_DIR --plan plan.json
    python tools/postmortem.py RUN_DIR --expected-rids r0,r1,r2

Reads every flight-recorder file (``*.flr``) plus the fsynced journals
(``fired.json``, ``train_log.jsonl``, ``health.jsonl``,
``journal.jsonl``) under RUN_DIR and reconstructs:

- the per-worker last-committed-step table (exact: the recorder commits
  a step's phases at compute end, before any log/checkpoint);
- who-died-first ordering across workers and incarnations;
- the hang / NaN / shed / preemption event narrative;
- the exactly-once cross-check against the serving request journal.

``--plan`` (a FaultPlan JSON file, or the literal JSON) additionally
verifies the reconstruction against the injected plan: every planned
fault fired, nothing unplanned fired, deaths in the injected order.

Exit code: 0 for a coherent story (and a matching plan, when given);
1 when the story contradicts itself or the plan; 2 when RUN_DIR holds
no recorder files at all.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_plan(arg):
    """--plan accepts a path to a FaultPlan JSON (or a report carrying
    ``events``) or the literal JSON string."""
    if arg is None:
        return None
    text = arg
    if os.path.exists(arg):
        with open(arg) as f:
            text = f.read()
    rec = json.loads(text)
    if isinstance(rec, dict):
        rec = rec.get("events", rec.get("plan", {}).get("events", []))
    return [{"kind": e["kind"], "step": int(e["step"])} for e in rec]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("run_dir", help="directory holding *.flr recorder "
                                   "files and the run's journals")
    p.add_argument("--plan", default=None,
                   help="FaultPlan JSON (path or literal) to verify the "
                        "reconstruction against")
    p.add_argument("--expected-rids", default=None,
                   help="comma list scoping the serving exactly-once "
                        "cross-check")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--out", default=None, help="also write the report here")
    args = p.parse_args(argv)

    from paddle_tpu.observability import fleet

    rids = [r for r in (args.expected_rids or "").split(",") if r.strip()]
    report = fleet.postmortem_report(
        args.run_dir, plan=_load_plan(args.plan),
        expected_rids=rids or None)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(fleet.format_report(report))
    if report["recorder_files"] == 0:
        print(f"postmortem: no recorder files under {args.run_dir} "
              f"(was FLAGS_flight_recorder=on?)", file=sys.stderr)
        return 2
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
