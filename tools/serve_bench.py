#!/usr/bin/env python
"""Replay a request trace through the serving engine and report
tokens/s + tail latency.

    python tools/serve_bench.py                       # synthetic ragged trace
    python tools/serve_bench.py --requests 16 --max-batch 8 --json
    python tools/serve_bench.py --trace trace.jsonl --arrivals
    python tools/serve_bench.py --sequential          # max_batch=1 baseline
    # shared-system-prompt workload x 8 users, radix tree armed:
    python tools/serve_bench.py --prefix-trace 8 --share-ratio 0.8 \
        --prompt-len 64 --prefix-cache
    python tools/serve_bench.py --chunked-prefill 32 --speculative 4

Trace file: one JSON object per line —
    {"rid": "r0", "prompt": [1, 5, 9], "max_new_tokens": 8,
     "arrival_s": 0.25}
``prompt_len`` (seeded random ids) may replace ``prompt``; ``arrival_s``
is honored only under ``--arrivals`` (otherwise the trace is closed-loop:
everything submitted up front). Without ``--trace`` a deterministic
ragged trace is synthesized from ``--seed``.

The report carries throughput (tokens/s over generated tokens), exact
p50/p99 request latency and TTFT from the request timeline, the compile
budget check (distinct executable signatures vs registered buckets — the
O001-silence criterion), preemption/spill counts, and per-phase totals.
``--json`` emits it as one machine-readable object on stdout;
``--timeline`` additionally writes the per-request JSONL records.

Resilience / SLO gating: ``--deadline-ms`` stamps every request with a
deadline (per-trace ``deadline_s`` fields win), the report then carries
``slo_attainment_pct`` (fraction of deadline-carrying requests answered
in time) and ``shed_rate``; ``--fail-on-slo <pct>`` exits nonzero when
attainment lands below the target — the CI gate
``tests/test_serve_drill.py`` runs. ``--max-waiting`` bounds admission
(rejected requests count against the SLO).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def synth_trace(n, seed, vocab, lo, hi, max_new):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        plen = int(rng.integers(lo, hi + 1))
        out.append({"rid": f"r{i}",
                    "prompt": rng.integers(0, vocab, plen).tolist(),
                    "max_new_tokens": int(max_new),
                    "arrival_s": round(i * 0.01, 4)})
    return out


def prefix_trace(n_users, seed, vocab, share_ratio, prompt_len, max_new):
    """The production-shaped workload: one shared system prompt of
    ``share_ratio * prompt_len`` tokens, ``n_users`` requests that each
    append a private suffix — the trace every prefix-hit-rate x
    tokens/s curve in BENCH_SERVE replays. ``share_ratio=0`` degrades
    to fully private prompts of the same length."""
    rng = np.random.default_rng(seed)
    shared_len = int(round(share_ratio * prompt_len))
    shared = rng.integers(0, vocab, shared_len).tolist()
    out = []
    for i in range(n_users):
        suffix = rng.integers(0, vocab,
                              max(1, prompt_len - shared_len)).tolist()
        out.append({"rid": f"u{i}", "prompt": shared + suffix,
                    "max_new_tokens": int(max_new),
                    "arrival_s": round(i * 0.01, 4)})
    return out


def load_trace(path, seed, vocab):
    rng = np.random.default_rng(seed)
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "prompt" not in rec:
                rec["prompt"] = rng.integers(
                    0, vocab, int(rec.pop("prompt_len"))).tolist()
            rec.setdefault("rid", f"r{i}")
            out.append(rec)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--trace", help="request-trace JSONL to replay")
    p.add_argument("--requests", type=int, default=8,
                   help="synthetic trace size (no --trace)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prompt-lo", type=int, default=4)
    p.add_argument("--prompt-hi", type=int, default=32)
    p.add_argument("--max-new", type=int, default=8)
    p.add_argument("--arrivals", action="store_true",
                   help="honor per-request arrival_s offsets")
    p.add_argument("--sequential", action="store_true",
                   help="max_batch=1: the sequential (still KV-cached) "
                        "baseline")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request deadline applied to the whole trace "
                        "(per-record deadline_s fields win)")
    p.add_argument("--fail-on-slo", type=float, default=None, metavar="PCT",
                   help="exit nonzero when SLO attainment < PCT")
    # synthetic prefix-sharing workload (ISSUE 13)
    p.add_argument("--prefix-trace", type=int, default=None, metavar="N",
                   help="generate a shared-system-prompt trace for N "
                        "users instead of the ragged trace (see "
                        "--share-ratio / --prompt-len)")
    p.add_argument("--share-ratio", type=float, default=0.8,
                   help="fraction of each --prefix-trace prompt that is "
                        "the common system prefix")
    p.add_argument("--prompt-len", type=int, default=64,
                   help="total prompt length per --prefix-trace user")
    # engine knobs
    p.add_argument("--block-size", type=int, default=4)
    p.add_argument("--num-blocks", type=int, default=64)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-waiting", type=int, default=None,
                   help="bounded admission: reject past this queue depth")
    p.add_argument("--prefix-cache", action="store_true",
                   help="arm the radix prefix-sharing KV cache")
    p.add_argument("--chunked-prefill", type=int, default=0, metavar="T",
                   help="chunked-prefill token budget (0 = one-shot)")
    p.add_argument("--speculative", type=int, default=0, metavar="G",
                   help="speculative draft depth gamma (0 = off, "
                        "-1 = autotuned)")
    # model knobs (tiny CPU-mesh GPT by default)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query KV heads (0 = MHA)")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--max-pos", type=int, default=128)
    p.add_argument("--timeline", help="write per-request JSONL here")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.observability import metrics, request_timeline
    from paddle_tpu.serving import Request, ServingEngine
    from paddle_tpu.text.models.gpt import GPTForCausalLM, gpt_tiny

    say = (lambda *a: print(*a, file=sys.stderr)) if args.json else print

    if args.trace:
        trace = load_trace(args.trace, args.seed, args.vocab)
    elif args.prefix_trace:
        trace = prefix_trace(args.prefix_trace, args.seed, args.vocab,
                             args.share_ratio, args.prompt_len,
                             args.max_new)
    else:
        trace = synth_trace(args.requests, args.seed, args.vocab,
                            args.prompt_lo, args.prompt_hi, args.max_new)
    default_deadline = (args.deadline_ms / 1e3
                        if args.deadline_ms is not None else None)
    requests = [Request(rid=r["rid"],
                        prompt_ids=np.asarray(r["prompt"], np.int32),
                        max_new_tokens=int(r["max_new_tokens"]),
                        eos_token_id=r.get("eos_token_id"),
                        arrival_s=float(r.get("arrival_s", 0.0)),
                        deadline_s=r.get("deadline_s", default_deadline),
                        priority=int(r.get("priority", 0)))
                for r in trace]

    paddle.seed(args.seed)
    cfg = gpt_tiny(vocab_size=args.vocab, hidden_size=args.hidden,
                   num_layers=args.layers, num_heads=args.heads,
                   num_kv_heads=args.kv_heads or None,
                   max_position_embeddings=args.max_pos)
    model = GPTForCausalLM(cfg)
    rt = request_timeline.reset_default()
    eng = ServingEngine(model, block_size=args.block_size,
                        num_blocks=args.num_blocks,
                        max_batch=1 if args.sequential else args.max_batch,
                        max_waiting=args.max_waiting,
                        prefix_cache=args.prefix_cache,
                        chunked_prefill=args.chunked_prefill,
                        speculative=args.speculative)
    tiers = [t for t, on in (("prefix", args.prefix_cache),
                             ("chunked", args.chunked_prefill),
                             ("spec", args.speculative)) if on]
    say(f"replaying {len(requests)} request(s) through "
        f"{'sequential' if args.sequential else 'continuous-batching'} "
        f"engine (blocks {args.num_blocks}x{args.block_size}, "
        f"max_batch {eng.sched.max_batch}"
        f"{', tiers: ' + '+'.join(tiers) if tiers else ''})")
    t0 = time.perf_counter()
    eng.serve(requests, respect_arrivals=args.arrivals)
    wall_s = time.perf_counter() - t0

    summary = rt.summary()
    new_tokens = summary["new_tokens"]
    report = {
        "requests": len(requests),
        "new_tokens": new_tokens,
        "wall_s": round(wall_s, 4),
        "tokens_per_s": round(new_tokens / wall_s, 2) if wall_s else 0.0,
        "p50_ms": summary["p50_ms"],
        "p99_ms": summary["p99_ms"],
        "ttft_p50_ms": summary["ttft_p50_ms"],
        "ttft_p99_ms": summary["ttft_p99_ms"],
        "phases": summary["phases"],
        "preemptions": summary["preemptions"],
        "kv_spills": metrics.counter("serving.kv_spills").get(),
        "outcomes": summary["outcomes"],
        "slo_attainment_pct": summary["slo_attainment_pct"],
        "shed_rate": summary["shed_rate"],
        "compile_report": eng.compile_report(),
        "mode": "sequential" if args.sequential else "continuous",
    }
    if args.prefix_cache:
        report["prefix_report"] = eng.prefix_report()
    if args.speculative:
        report["spec_report"] = eng.spec_report()
    if args.timeline:
        n = rt.export_jsonl(args.timeline)
        say(f"wrote {n} request record(s) to {args.timeline}")
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"tokens/s          {report['tokens_per_s']}")
        print(f"latency p50/p99   {report['p50_ms']} / "
              f"{report['p99_ms']} ms")
        print(f"ttft p50/p99      {report['ttft_p50_ms']} / "
              f"{report['ttft_p99_ms']} ms")
        print(f"preemptions       {report['preemptions']} "
              f"(spills {report['kv_spills']})")
        cr = report["compile_report"]
        ext = (f", extend {cr['extend_signatures']}"
               if cr.get("extend_signatures") else "")
        print(f"compiles          prefill {cr['prefill_signatures']}/"
              f"{len(cr['prefill_buckets'])} buckets, decode "
              f"{cr['decode_signatures']}/{len(cr['decode_buckets'])} "
              f"buckets{ext}, O001 fired: {cr['o001_fired']}")
        if report["slo_attainment_pct"] is not None:
            print(f"slo attainment    {report['slo_attainment_pct']}% "
                  f"(shed rate {report['shed_rate']}, "
                  f"outcomes {report['outcomes']})")
        if "prefix_report" in report:
            pr = report["prefix_report"]
            print(f"prefix cache      hit rate {pr['hit_rate']}, "
                  f"{pr['tree_nodes']} tree nodes, peak blocks "
                  f"{pr['peak_blocks_used']}")
        if "spec_report" in report:
            sr = report["spec_report"]
            print(f"speculative       gamma {sr['gamma']} "
                  f"({sr['drafter']}), accept rate "
                  f"{sr['accept_rate']}, {sr['tokens_per_verify']} "
                  f"tokens/verify")
    if report["compile_report"]["o001_fired"]:
        return 1
    if (args.fail_on_slo is not None
            and (report["slo_attainment_pct"] is None
                 or report["slo_attainment_pct"] < args.fail_on_slo)):
        say(f"SLO attainment {report['slo_attainment_pct']}% below the "
            f"--fail-on-slo target {args.fail_on_slo}%")
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
