#!/usr/bin/env python
"""Training-health drill CLI: inject -> detect -> decide -> recover -> prove.

    python tools/health_drill.py --quick            # all five scenarios
    python tools/health_drill.py --scenario nan     # one scenario
    python tools/health_drill.py --quick --json     # report JSON on stdout
    python tools/health_drill.py --quick --clean-steps 200

Scenarios (paddle_tpu/fault/health_drill.py):

- nan    : inject_nan -> sentinel detects same step -> rewind to
           last-good -> replay skipping the poisoned batch -> final loss
           BITWISE-equal to a clean run that never saw that batch
- spike  : inject_loss_spike -> sentinel (rolling median) -> skip_batch
           (the in-graph gate already blocked the update) -> parity
- hang   : inject_hang stalls a dispatch -> wall-clock watchdog ->
           elastic relaunch (exit 103) -> resume -> parity
- sdc    : inject_sdc flips one bit in one gradient leaf of a canary
           re-execution -> detected at the next canary step (<= K) ->
           rewind WITHOUT batch skip -> parity
- clean  : 200 steps, sentinel + canary armed, zero injected faults —
           zero anomalies tolerated (the false-positive gate)

Exits nonzero when any scenario fails to detect, recover, or match.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--quick", action="store_true",
                   help="run all five scenarios at tier-1-safe sizes")
    p.add_argument("--scenario", choices=("nan", "spike", "hang", "sdc",
                                          "clean"), default=None,
                   help="run a single scenario")
    p.add_argument("--workdir", default=None,
                   help="drill scratch dir (default: a fresh temp dir)")
    p.add_argument("--clean-steps", type=int, default=200,
                   help="length of the false-positive gate run")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--out", default=None, help="also write the report here")
    args = p.parse_args(argv)

    from paddle_tpu.fault import health_drill

    scenarios = [args.scenario] if args.scenario else None
    workdir = args.workdir or tempfile.mkdtemp(prefix="health_drill_")
    report = health_drill.run_health_drill(
        workdir, scenarios=scenarios, clean_steps=args.clean_steps)
    report["workdir"] = workdir

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(health_drill.report_summary(report))
        print(json.dumps({
            "metric": "health_drill", "ok": report["ok"],
            "scenarios": {k: v.get("ok")
                          for k, v in report["scenarios"].items()}}))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
