#!/usr/bin/env python
"""Lint the traced graphs of the repo's example models (and, with --all,
the Pallas kernel configs and the source tree) with paddle_tpu.analysis.

    python tools/lint_graph.py --model bert          # one model, CPU, fast
    python tools/lint_graph.py --all                 # models + kernels + AST
    python tools/lint_graph.py --model gpt --min-severity info

Exits nonzero when any error-severity diagnostic is found — the CI gate
that needs no TPU. Clean models print their diagnostic count (0) and the
jaxpr size, so regressions in graph hygiene show up in review.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The overlap model lints a decomposed collective over an 8-device virtual
# mesh (same provisioning as tests/conftest.py); no-op if jax is already
# initialized (the in-process selfcheck run has its own 8 devices).
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_layer(layer, args, where):
    from paddle_tpu.framework.functional import functional_call, get_params
    from paddle_tpu.analysis import lint_jaxpr
    layer.eval()  # inference view: dropout off, no host RNG pulls
    params = get_params(layer)
    closed = jax.make_jaxpr(
        lambda p, *a: functional_call(layer, p, *a))(params, *args)
    diags = lint_jaxpr(closed, where=where)
    return diags, len(closed.jaxpr.eqns)


def lint_bert():
    from paddle_tpu.text.models.bert import Bert, bert_tiny
    ids = jnp.zeros((2, 128), jnp.int32)
    return _lint_layer(Bert(bert_tiny()), (ids,), "bert")


def lint_gpt():
    from paddle_tpu.text.models.gpt import GPT, gpt_tiny
    ids = jnp.zeros((2, 128), jnp.int32)
    return _lint_layer(GPT(gpt_tiny()), (ids,), "gpt")


def lint_mlp():
    from paddle_tpu import nn
    net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 10))
    return _lint_layer(net, (jnp.zeros((4, 64), jnp.float32),), "mlp")


def lint_offload():
    """The offload streaming-update block program (framework/offload.py):
    must stay free of in-graph memory-kind transfers (J012) — all
    host<->device movement happens at dispatch level."""
    from paddle_tpu import nn
    from paddle_tpu.analysis import lint_jaxpr
    from paddle_tpu.framework import offload
    from paddle_tpu.framework.functional import get_params
    from paddle_tpu.optimizer import AdamW

    net = nn.Sequential(nn.Linear(32, 64), nn.Tanh(), nn.Linear(64, 8))
    params = get_params(net)
    opt = AdamW(learning_rate=1e-3)
    su = offload.StreamingUpdate(opt)
    state = su.init_state(params)
    grads = {k: jnp.ones_like(v) for k, v in params.items()}
    names = offload.group_by_block(list(params))[0][1]
    p_blk = {n: params[n] for n in names}
    g_blk = {n: grads[n] for n in names}
    st_blk = {n: {k: jax.device_put(v, params[n].sharding)
                  for k, v in state["param_states"][n].items()}
              for n in names}
    closed = jax.make_jaxpr(su._block_fn.__wrapped__)(
        p_blk, g_blk, st_blk, state["step"], jnp.float32(1e-3))
    diags = lint_jaxpr(closed, donate_argnums=(0, 1, 2), where="offload")
    return diags, len(closed.jaxpr.eqns)


def lint_overlap():
    """The decomposed-collective-matmul programs (distributed/overlap.py):
    a Megatron-SP column+row pair through the bidirectional ppermute
    pipelines, traced fwd+grad and linted (J012/J013/J014 — the
    decomposed loops must not themselves trip the overlap rules), plus
    the static ICI accounting (C001-C003) of each hop plan at a
    production-ish shape."""
    import numpy as np
    from jax.sharding import Mesh
    from paddle_tpu.analysis import (comm_check, lint_jaxpr)
    from paddle_tpu.distributed import overlap

    if jax.device_count() < 2:
        print("  (skipped: needs >=2 devices for the mp mesh; "
              "run under the 8-device virtual CPU platform)")
        return [], 0
    n = 8 if jax.device_count() >= 8 else 2
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(1, 1, 1, 1, n),
                ("pp", "dp", "sharding", "sep", "mp"))
    b, s, d, f = 2, 8 * n, 16, 32
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((d, f)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((f, d)), jnp.float32)

    def sp_pair(x, w1, w2):
        h = overlap.allgather_matmul(x, w1, mesh=mesh, chunks=1)
        h = jax.nn.gelu(h)
        return overlap.matmul_reduce_scatter(h, w2, mesh=mesh, chunks=1)

    def loss(x, w1, w2):
        return jnp.sum(sp_pair(x, w1, w2) ** 2)

    closed = jax.make_jaxpr(jax.value_and_grad(loss, argnums=(1, 2)))(
        x, w1, w2)
    diags = lint_jaxpr(closed, where="overlap")
    # static hop-plan accounting at a production-ish shape (GPT-1.3B
    # layer through mp=4: B*S_local*K chunks well over the latency floor)
    for spec in (
            comm_check.spec_for_allgather_matmul(
                8, 512, 2048, 2048, 4, 2),
            comm_check.spec_for_matmul_reduce_scatter(
                8, 512, 2048, 2048, 4, 2)):
        cd = comm_check.check_comm_spec(spec)
        print(f"  comm spec {spec.name}: {spec.hops} hops x "
              f"{spec.bytes_per_hop / 2**20:.2f} MiB, "
              f"{len(cd)} diagnostic(s)")
        for d in cd:
            print("    " + d.format())
        diags += cd
    return diags, len(closed.jaxpr.eqns)


MODELS = {"bert": lint_bert, "gpt": lint_gpt, "mlp": lint_mlp,
          "offload": lint_offload, "overlap": lint_overlap}

_SEV_RANK = {"info": 0, "warning": 1, "error": 2}


def run(models, with_kernels=False, with_repo=False, min_severity="info"):
    from paddle_tpu.analysis import check_kernel_spec, repo_lint
    from paddle_tpu.core import flags as core_flags
    all_diags = []
    for name in models:
        diags, n_eqns = MODELS[name]()
        shown = [d for d in diags
                 if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]]
        print(f"== {name}: {n_eqns} eqns, {len(diags)} diagnostic(s)")
        for d in shown:
            print("  " + d.format())
        all_diags += diags
    if with_kernels:
        from paddle_tpu.analysis import spec_for_flash_packed, spec_for_flash
        from paddle_tpu.ops._pallas.flash_attention_packed import (
            _pick_blocks_packed, pack_group, HEAD_D)
        print("== pallas kernel configs")
        for sq, sk, h in ((512, 512, 12), (1024, 1024, 16)):
            g = pack_group(h) or 2
            dp = g * HEAD_D
            for bwd in (False, True):
                bq, bk = _pick_blocks_packed(sq, sk, dp, bwd=bwd)
                spec = spec_for_flash_packed(sq, sk, dp, bq, bk, g, bwd=bwd)
                diags = check_kernel_spec(spec)
                tag = f"{spec.name} sq{sq} sk{sk} g{g} blocks {bq}x{bk}"
                print(f"  {tag}: {len(diags)} diagnostic(s)")
                for d in diags:
                    print("    " + d.format())
                all_diags += diags
        # the conv family at its default blocks for the byte-dominant
        # ResNet shapes (fwd + wgrad; dgrad reuses the fwd kernel spec)
        import numpy as np
        from paddle_tpu.analysis import (spec_for_conv_matmul,
                                         spec_for_conv3x3)
        from paddle_tpu.ops._pallas import conv as pconv
        print("== pallas conv configs (RESNET50_TOP3_SHAPES, bf16)")
        bf16 = np.dtype("bfloat16")
        for kind, n, h, w, cin, cout, s_ in pconv.RESNET50_TOP3_SHAPES:
            if kind == "conv1x1":
                m = n * ((h + s_ - 1) // s_) * ((w + s_ - 1) // s_)
                bm = pconv._pick_block_m(m, cin, cout, jnp.bfloat16)
                specs = [spec_for_conv_matmul(m, cin, cout, bm, dtype=bf16),
                         spec_for_conv_matmul(m, cin, cout, bm, dtype=bf16,
                                              wgrad=True)]
                cfg = f"m{m} ci{cin} co{cout} block_m {bm}"
            else:
                ho = (h + 2 - 3) // s_ + 1
                bh = pconv._pick_block_h(ho, n, h, w, cin, cout, s_,
                                         jnp.bfloat16)
                specs = [spec_for_conv3x3(n, h, w, cin, cout, bh, s_,
                                          dtype=bf16),
                         spec_for_conv3x3(n, h, w, cin, cout, bh, s_,
                                          dtype=bf16, wgrad=True)]
                cfg = f"n{n} {h}x{w} ci{cin} co{cout} s{s_} block_h {bh}"
            for spec in specs:
                diags = check_kernel_spec(spec)
                print(f"  {spec.name} {cfg}: {len(diags)} diagnostic(s)")
                for d in diags:
                    print("    " + d.format())
                all_diags += diags
    if with_repo:
        print("== repo AST lint (paddle_tpu/)")
        diags = repo_lint.lint_tree(REPO)
        for d in diags:
            if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
                print("  " + d.format())
        all_diags += diags
        unknown = core_flags.unknown_env_flags()
        if unknown:
            print(f"  note: unrecognized FLAGS_* env vars: {unknown}")
    errors = [d for d in all_diags if d.severity == "error"]
    print(f"total: {len(all_diags)} diagnostic(s), {len(errors)} error(s)")
    return 1 if errors else 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", choices=sorted(MODELS), action="append",
                   help="model graph(s) to lint (repeatable)")
    p.add_argument("--all", action="store_true",
                   help="lint every model + pallas kernel configs + repo AST")
    p.add_argument("--min-severity", choices=["info", "warning", "error"],
                   default="info", help="only print findings at or above")
    a = p.parse_args(argv)
    if a.all:
        models = sorted(MODELS)
    else:
        models = a.model or ["bert"]
    return run(models, with_kernels=a.all, with_repo=a.all,
               min_severity=a.min_severity)


if __name__ == "__main__":
    sys.exit(main())
