#!/usr/bin/env python
"""Lint the traced graphs of the repo's example models (and, with --all,
the Pallas kernel configs and the source tree) with paddle_tpu.analysis.

    python tools/lint_graph.py --model bert          # one model, CPU, fast
    python tools/lint_graph.py --all                 # models + kernels + AST
    python tools/lint_graph.py --model gpt --min-severity info
    python tools/lint_graph.py --matrix              # tier-flag matrix gate
    python tools/lint_graph.py --matrix --json       # machine-readable
    python tools/lint_graph.py --hlo                 # compiled-HLO X-rules
    python tools/lint_graph.py --passes              # pass-pipeline G-rules

Exits nonzero when any error-severity diagnostic is found — the CI gate
that needs no TPU. Clean models print their diagnostic count (0) and the
jaxpr size, so regressions in graph hygiene show up in review.

``--matrix`` enumerates every supported combination of the six tier
flags (offload_optimizer × comm_overlap × multislice × cp_nested_ring ×
pallas_conv × remat), builds each composition's StepPlan on the 8-device
virtual mesh,
and verifies it with ``analysis/plan_check`` (sharding-flow S-rules +
donation-lifetime D-rules) + ``analysis/comm_check`` hop plans +
``tools/hbm_budget.py`` capacity, AOT-compiles each trace-distinct step
and runs the compiled-HLO X-rules (``analysis/hlo_check`` — skip with
``--no-hlo``) — then runs the ten multichip dryrun scenarios (skipped
with a note on legacy jax, where they cannot trace). ``--hlo`` runs the
X-rules standalone over the representative composed steps plus a seeded
X001 self-test. ``--passes`` runs the step-compiler pass-pipeline
verifier standalone: the ordered pass list and per-pass contract hashes,
every tier combo (both sentinel arms) composed plan-only through
``framework/step_pipeline.py`` and checked with the G-rules
(``analysis/pass_check``), plus seeded self-tests that G001/G002/G004
each fire on a bad composition. ``--json`` switches stdout to one
machine-readable report for CI (schema v3: v2's ``schema_version`` +
per-family ``rule_index``, plus the ``passes`` section — ordered pass
list, contract hashes, per-combo composed-plan hash — so CI can diff
pipeline composition across PRs).
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The overlap model lints a decomposed collective over an 8-device virtual
# mesh (same provisioning as tests/conftest.py); no-op if jax is already
# initialized (the in-process selfcheck run has its own 8 devices).
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_layer(layer, args, where):
    from paddle_tpu.framework.functional import functional_call, get_params
    from paddle_tpu.analysis import lint_jaxpr
    layer.eval()  # inference view: dropout off, no host RNG pulls
    params = get_params(layer)
    closed = jax.make_jaxpr(
        lambda p, *a: functional_call(layer, p, *a))(params, *args)
    diags = lint_jaxpr(closed, where=where)
    return diags, len(closed.jaxpr.eqns)


def lint_bert():
    from paddle_tpu.text.models.bert import Bert, bert_tiny
    ids = jnp.zeros((2, 128), jnp.int32)
    return _lint_layer(Bert(bert_tiny()), (ids,), "bert")


def lint_gpt():
    from paddle_tpu.text.models.gpt import GPT, gpt_tiny
    ids = jnp.zeros((2, 128), jnp.int32)
    return _lint_layer(GPT(gpt_tiny()), (ids,), "gpt")


def lint_mlp():
    from paddle_tpu import nn
    net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 10))
    return _lint_layer(net, (jnp.zeros((4, 64), jnp.float32),), "mlp")


def lint_offload():
    """The offload streaming-update block program (framework/offload.py):
    must stay free of in-graph memory-kind transfers (J012) — all
    host<->device movement happens at dispatch level."""
    from paddle_tpu import nn
    from paddle_tpu.analysis import lint_jaxpr
    from paddle_tpu.framework import offload
    from paddle_tpu.framework.functional import get_params
    from paddle_tpu.optimizer import AdamW

    net = nn.Sequential(nn.Linear(32, 64), nn.Tanh(), nn.Linear(64, 8))
    params = get_params(net)
    opt = AdamW(learning_rate=1e-3)
    su = offload.StreamingUpdate(opt)
    state = su.init_state(params)
    grads = {k: jnp.ones_like(v) for k, v in params.items()}
    names = offload.group_by_block(list(params))[0][1]
    p_blk = {n: params[n] for n in names}
    g_blk = {n: grads[n] for n in names}
    st_blk = {n: {k: jax.device_put(v, params[n].sharding)
                  for k, v in state["param_states"][n].items()}
              for n in names}
    closed = jax.make_jaxpr(su._block_fn.__wrapped__)(
        p_blk, g_blk, st_blk, state["step"], jnp.float32(1e-3))
    diags = lint_jaxpr(closed, donate_argnums=(0, 1, 2), where="offload")
    return diags, len(closed.jaxpr.eqns)


def lint_overlap():
    """The decomposed-collective-matmul programs (distributed/overlap.py):
    a Megatron-SP column+row pair through the bidirectional ppermute
    pipelines, traced fwd+grad and linted (J012/J013/J014 — the
    decomposed loops must not themselves trip the overlap rules), plus
    the static ICI accounting (C001-C003) of each hop plan at a
    production-ish shape."""
    import numpy as np
    from jax.sharding import Mesh
    from paddle_tpu.analysis import (comm_check, lint_jaxpr)
    from paddle_tpu.distributed import overlap

    if jax.device_count() < 2:
        print("  (skipped: needs >=2 devices for the mp mesh; "
              "run under the 8-device virtual CPU platform)")
        return [], 0
    n = 8 if jax.device_count() >= 8 else 2
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(1, 1, 1, 1, n),
                ("pp", "dp", "sharding", "sep", "mp"))
    b, s, d, f = 2, 8 * n, 16, 32
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((d, f)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((f, d)), jnp.float32)

    def sp_pair(x, w1, w2):
        h = overlap.allgather_matmul(x, w1, mesh=mesh, chunks=1)
        h = jax.nn.gelu(h)
        return overlap.matmul_reduce_scatter(h, w2, mesh=mesh, chunks=1)

    def loss(x, w1, w2):
        return jnp.sum(sp_pair(x, w1, w2) ** 2)

    closed = jax.make_jaxpr(jax.value_and_grad(loss, argnums=(1, 2)))(
        x, w1, w2)
    diags = lint_jaxpr(closed, where="overlap")
    # static hop-plan accounting at a production-ish shape (GPT-1.3B
    # layer through mp=4: B*S_local*K chunks well over the latency floor)
    for spec in (
            comm_check.spec_for_allgather_matmul(
                8, 512, 2048, 2048, 4, 2),
            comm_check.spec_for_matmul_reduce_scatter(
                8, 512, 2048, 2048, 4, 2)):
        cd = comm_check.check_comm_spec(spec)
        print(f"  comm spec {spec.name}: {spec.hops} hops x "
              f"{spec.bytes_per_hop / 2**20:.2f} MiB, "
              f"{len(cd)} diagnostic(s)")
        for d in cd:
            print("    " + d.format())
        diags += cd
    return diags, len(closed.jaxpr.eqns)


def lint_fault():
    """The fault-drill configuration (paddle_tpu/fault/): the drill
    trainer's composed train step traced + jaxpr-linted + verified
    against its declared StepPlan (same gate every other tier gets), the
    GUARDED step (FLAGS_health_sentinel=on — fused stats + in-graph
    update gate) through the identical rules, the quick drill's
    deterministic FaultPlan statically validated (F002), and the health
    tier's own static rules: the Guardian policy table (F004) and the
    SDC canary cadence (F005)."""
    import numpy as np
    from paddle_tpu.analysis import lint_jaxpr, plan_check
    from paddle_tpu.fault import _trainer, drill, guardian, health, injection

    ts, batches = _trainer.build_step("quick")
    closed, donate = ts.trace_step(batches[0])
    diags = lint_jaxpr(closed, donate_argnums=donate, where="fault")
    diags += plan_check.check_plan(ts.plan, closed, donate_argnums=donate,
                                   where="fault")
    cfg = drill.quick_config()
    plan = injection.FaultPlan.from_seed(
        cfg["seed"], cfg["total_steps"], n_kills=cfg["n_kills"],
        kinds=cfg["kinds"])
    pd = injection.check_plan(plan, cfg["total_steps"])
    print(f"  fault plan {plan!r}: {len(pd)} diagnostic(s)")
    diags += pd

    # the guarded step: sentinel fused in, same jaxpr + plan gates
    gts, gbatches = _trainer.build_step("quick", health=True)
    ids, labels = gbatches[0]
    gbatch = (ids, labels, np.asarray([1.0], np.float32))
    gclosed, gdonate = gts.trace_step(gbatch)
    gd = lint_jaxpr(gclosed, donate_argnums=gdonate, where="fault.guarded")
    gd += plan_check.check_plan(gts.plan, gclosed, donate_argnums=gdonate,
                                where="fault.guarded")
    print(f"  guarded step (sentinel fused): {len(gclosed.jaxpr.eqns)} "
          f"eqns, {len(gd)} diagnostic(s)")
    diags += gd

    # health-tier static rules over the quick drill's configuration
    hcfg = drill.quick_health_config()
    hd = health.check_health_plan(guardian.DEFAULT_POLICIES)
    hd += health.check_canary(3, hcfg["total_steps"])
    print(f"  health plan (F004) + canary cadence (F005): "
          f"{len(hd)} diagnostic(s)")
    diags += hd
    hplan = injection.FaultPlan.from_seed(
        hcfg["seed"], hcfg["total_steps"], n_kills=hcfg["n_kills"],
        kinds=hcfg["kinds"])
    hplan = drill._dodge_resume_boundaries(
        hplan, hcfg["ckpt_every"], hcfg["total_steps"])
    hpd = injection.check_plan(hplan, hcfg["total_steps"])
    print(f"  health drill plan {hplan!r}: {len(hpd)} diagnostic(s)")
    diags += hpd
    return diags, len(closed.jaxpr.eqns) + len(gclosed.jaxpr.eqns)


def lint_serving():
    """The serving engine's bucketed executables (paddle_tpu/serving/):
    prefill (flash forward + paged KV scatter), decode (paged gather +
    single-query attention + in-program KV write), and — with the three
    ISSUE-13 throughput tiers armed — extend (chunked/suffix prefill),
    verify (speculative decode-gamma), and the ModelDrafter's draft
    step, each traced at its smallest buckets through the jaxpr linter;
    plus the declared dispatch plan (prefill/chunk/draft/verify/decode/
    spill/restore donation sequence with the COW-shared page discipline,
    rule D005) verified by plan_check and the compiled decode + verify
    modules through the X pass."""
    import paddle_tpu as paddle
    from paddle_tpu.analysis import lint_jaxpr, plan_check
    from paddle_tpu.serving import ModelDrafter, ServingEngine
    from paddle_tpu.text.models.gpt import GPTForCausalLM, gpt_tiny

    paddle.seed(0)
    cfg = gpt_tiny(vocab_size=128, hidden_size=48, num_layers=2,
                   num_heads=4, max_position_embeddings=64)
    model = GPTForCausalLM(cfg)
    paddle.seed(1)
    drafter = GPTForCausalLM(gpt_tiny(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
        max_position_embeddings=64))
    # all three tiers armed: the full plan (incl. D005's cow_shared
    # declaration) and every executable family get verified
    eng = ServingEngine(model, block_size=4, num_blocks=32, max_batch=4,
                        prefix_cache=True, chunked_prefill=8,
                        speculative=2, drafter=ModelDrafter(drafter))
    diags, n_eqns = [], 0
    traced = eng.trace_steps()
    for name, (closed, donate) in traced.items():
        d = lint_jaxpr(closed, donate_argnums=donate,
                       where=f"serving.{name}")
        print(f"  serving.{name}: {len(closed.jaxpr.eqns)} eqns, "
              f"{len(d)} diagnostic(s)")
        diags += d
        n_eqns += len(closed.jaxpr.eqns)
    pd = plan_check.check_plan(eng.plan, traced["decode"][0],
                               donate_argnums=traced["decode"][1],
                               where="serving")
    print(f"  serving plan ({len(eng.plan.nodes)} nodes, cow_shared="
          f"{eng.plan.flags.get('cow_shared_buffers')!r}): "
          f"{len(pd)} diagnostic(s)")
    diags += pd
    # compiled-HLO pass (X-rules): the single-partition decode and
    # verify modules must build with zero collectives and both
    # page-pool donations realized as aliases
    from paddle_tpu.analysis import hlo_check
    for label, (compiled, donated) in (
            ("decode", eng.compile_decode()),
            ("verify", eng.compile_extend(verify=True))):
        facts = hlo_check.collect_hlo_facts(compiled)
        xd = hlo_check.check_hlo(eng.plan, facts, donated_leaves=donated,
                                 where=f"serving.{label}.hlo")
        print(f"  serving.{label} compiled HLO: {facts.to_json()}, "
              f"{len(xd)} diagnostic(s)")
        diags += xd
    return diags, n_eqns


def _multislice_micro_step(mode: str = "hierarchical"):
    """A tiny GPT TrainStep on the 2-slice x 4-device virtual mesh with
    the 2-tier grad reduction active (shared by --model multislice and
    the --matrix multislice component)."""
    import paddle_tpu as paddle
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.multislice import SliceTopology
    from paddle_tpu.distributed.topology import set_hybrid_mesh
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    dp = 4 if jax.device_count() >= 8 else jax.device_count() // 2
    topo = SliceTopology(2, dp=dp)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash_attention=False)

    def loss_fn(m, p, b):
        ids, labels = b
        return functional_call(m, p, ids, labels, training=True)

    set_flags({"multislice": mode})
    set_hybrid_mesh(topo.mesh)
    ts = make_sharded_train_step(GPTForCausalLM(cfg), AdamW(1e-3), loss_fn,
                                 mesh=topo.mesh, fsdp_axis=None)
    ids = jnp.zeros((2 * dp, 16), jnp.int32)
    return topo, ts, (ids, ids)


def lint_multislice():
    """The multi-slice tier (distributed/multislice): the hierarchical
    2-tier TrainStep traced on the 2-slice virtual mesh through the jaxpr
    linter (incl. J015 — the reduction must not put a DCN collective in a
    loop body) and the S/D plan rules, the recorded hop plan through the
    C-rules (C001-C005), plus a self-test that the naive flat-over-DCN
    plan DOES fire C004 — the rule exists to catch exactly that plan."""
    from paddle_tpu.analysis import comm_check, lint_jaxpr, plan_check
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.topology import set_hybrid_mesh

    if jax.device_count() < 4:
        print("  (skipped: needs >=4 devices for the 2-slice mesh; "
              "run under the 8-device virtual CPU platform)")
        return [], 0
    try:
        topo, ts, batch = _multislice_micro_step("hierarchical")
        closed, donate = ts.trace_step(batch)
        diags = lint_jaxpr(closed, donate_argnums=donate,
                           where="multislice")
        diags += plan_check.check_plan(ts.plan, closed,
                                       donate_argnums=donate,
                                       where="multislice")
        for where, spec in ts.plan.comm_specs:
            cd = comm_check.check_comm_spec(spec)
            print(f"  comm spec {spec.name} [{spec.link}] axis="
                  f"{spec.axis}: {spec.hops} hops x "
                  f"{spec.bytes_per_hop / 1024:.1f} KiB, "
                  f"{len(cd)} diagnostic(s)")
            diags += [d for d in cd if d.severity == "error"]
    finally:
        set_flags({"multislice": "off"})
        set_hybrid_mesh(None)
    # production-shape hop plan: a 100 MiB DCN bucket over 2 slices of 64
    # chips — every stage must clear the C002/C005 latency floors
    bucket = 100 << 20
    for spec in (comm_check.spec_for_slice_reduce_scatter(bucket, 64),
                 comm_check.spec_for_dcn_allreduce(
                     bucket // 64, 2, reduced_from_bytes=bucket,
                     ici_size=64),
                 comm_check.spec_for_slice_all_gather(bucket, 64)):
        cd = comm_check.check_comm_spec(spec)
        print(f"  production {spec.name} [{spec.link}]: "
              f"{spec.payload_bytes / 2**20:.2f} MiB payload, "
              f"{len(cd)} diagnostic(s)")
        for d in cd:
            print("    " + d.format())
        diags += cd
    # C004 self-test: the naive plan (full bucket over DCN) must fire
    naive = comm_check.spec_for_dcn_allreduce(
        bucket, 2, reduced_from_bytes=bucket, ici_size=64)
    fired = [d for d in comm_check.check_comm_spec(naive)
             if d.rule == "C004"]
    print(f"  C004 on the naive flat-over-DCN plan: "
          f"{'fires' if fired else 'MISSING'}")
    if not fired:
        from paddle_tpu.analysis.jaxpr_lint import Diagnostic
        diags.append(Diagnostic(
            rule="C004", name="dcn-volume-blowup", severity="error",
            message="self-test: C004 did not fire on the naive "
                    "flat-allreduce-over-DCN hop plan",
            where="multislice"))
    return diags, len(closed.jaxpr.eqns)


MODELS = {"bert": lint_bert, "gpt": lint_gpt, "mlp": lint_mlp,
          "offload": lint_offload, "overlap": lint_overlap,
          "fault": lint_fault, "serving": lint_serving,
          "multislice": lint_multislice}

_SEV_RANK = {"info": 0, "warning": 1, "error": 2}

# --json report schema. v2 adds schema_version itself plus the
# rule_index section (family -> {count, ids -> per-id counts}) so CI can
# diff reports across PRs without re-deriving the rule taxonomy. v3 adds
# the passes section (ordered pass list, per-pass contract hashes,
# per-combo composed-plan hash) so CI can diff step-pipeline composition.
SCHEMA_VERSION = 3


def _rule_index(diags):
    """family -> {"count": N, "ids": {rule_id: count}} over a diagnostic
    list (Diagnostic objects or their to_json dicts)."""
    idx = {}
    for d in diags:
        rid = d["rule"] if isinstance(d, dict) else d.rule
        fam = idx.setdefault(rid[:1], {"count": 0, "ids": {}})
        fam["count"] += 1
        fam["ids"][rid] = fam["ids"].get(rid, 0) + 1
    return {k: {"count": v["count"],
                "ids": dict(sorted(v["ids"].items()))}
            for k, v in sorted(idx.items())}


def run(models, with_kernels=False, with_repo=False, min_severity="info",
        json_mode=False):
    """Model/kernel/repo lint pass. In json mode the human narration is
    redirected to stderr and stdout carries one parseable report."""
    if json_mode:
        import contextlib
        with contextlib.redirect_stdout(sys.stderr):
            rc, report = _run_impl(models, with_kernels, with_repo,
                                   min_severity)
        print(json.dumps(report, indent=2))
        return rc
    rc, _ = _run_impl(models, with_kernels, with_repo, min_severity)
    return rc


def _run_impl(models, with_kernels=False, with_repo=False,
              min_severity="info"):
    from paddle_tpu.analysis import check_kernel_spec, repo_lint
    from paddle_tpu.core import flags as core_flags
    all_diags = []
    report = {"models": {}}
    for name in models:
        diags, n_eqns = MODELS[name]()
        shown = [d for d in diags
                 if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]]
        print(f"== {name}: {n_eqns} eqns, {len(diags)} diagnostic(s)")
        for d in shown:
            print("  " + d.format())
        report["models"][name] = {
            "eqns": n_eqns, "diagnostics": [d.to_json() for d in diags]}
        all_diags += diags
    if with_kernels:
        report["kernels"] = []
        from paddle_tpu.analysis import spec_for_flash_packed, spec_for_flash
        from paddle_tpu.ops._pallas.flash_attention_packed import (
            _pick_blocks_packed, pack_group, HEAD_D)
        print("== pallas kernel configs")
        for sq, sk, h in ((512, 512, 12), (1024, 1024, 16)):
            g = pack_group(h) or 2
            dp = g * HEAD_D
            for bwd in (False, True):
                bq, bk = _pick_blocks_packed(sq, sk, dp, bwd=bwd)
                spec = spec_for_flash_packed(sq, sk, dp, bq, bk, g, bwd=bwd)
                diags = check_kernel_spec(spec)
                tag = f"{spec.name} sq{sq} sk{sk} g{g} blocks {bq}x{bk}"
                print(f"  {tag}: {len(diags)} diagnostic(s)")
                for d in diags:
                    print("    " + d.format())
                report["kernels"] += [d.to_json() for d in diags]
                all_diags += diags
        # the conv family at its default blocks for the byte-dominant
        # ResNet shapes (fwd + wgrad; dgrad reuses the fwd kernel spec)
        import numpy as np
        from paddle_tpu.analysis import (spec_for_conv_matmul,
                                         spec_for_conv3x3)
        from paddle_tpu.ops._pallas import conv as pconv
        print("== pallas conv configs (RESNET50_TOP3_SHAPES, bf16)")
        bf16 = np.dtype("bfloat16")
        for kind, n, h, w, cin, cout, s_ in pconv.RESNET50_TOP3_SHAPES:
            if kind == "conv1x1":
                m = n * ((h + s_ - 1) // s_) * ((w + s_ - 1) // s_)
                bm = pconv._pick_block_m(m, cin, cout, jnp.bfloat16)
                specs = [spec_for_conv_matmul(m, cin, cout, bm, dtype=bf16),
                         spec_for_conv_matmul(m, cin, cout, bm, dtype=bf16,
                                              wgrad=True)]
                cfg = f"m{m} ci{cin} co{cout} block_m {bm}"
            else:
                ho = (h + 2 - 3) // s_ + 1
                bh = pconv._pick_block_h(ho, n, h, w, cin, cout, s_,
                                         jnp.bfloat16)
                specs = [spec_for_conv3x3(n, h, w, cin, cout, bh, s_,
                                          dtype=bf16),
                         spec_for_conv3x3(n, h, w, cin, cout, bh, s_,
                                          dtype=bf16, wgrad=True)]
                cfg = f"n{n} {h}x{w} ci{cin} co{cout} s{s_} block_h {bh}"
            for spec in specs:
                diags = check_kernel_spec(spec)
                print(f"  {spec.name} {cfg}: {len(diags)} diagnostic(s)")
                for d in diags:
                    print("    " + d.format())
                report["kernels"] += [d.to_json() for d in diags]
                all_diags += diags
    if with_repo:
        print("== repo AST lint (paddle_tpu/ + tools/ + examples/ + "
              "__graft_entry__.py)")
        diags = repo_lint.lint_tree(REPO)
        for d in diags:
            if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
                print("  " + d.format())
        report["repo"] = [d.to_json() for d in diags]
        all_diags += diags
        from paddle_tpu.analysis import concurrency_check
        tdiags = concurrency_check.check_tree(REPO)
        print(f"== repo concurrency lint (T rules): {len(tdiags)} "
              "diagnostic(s)")
        for d in tdiags:
            if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
                print("  " + d.format())
        report["threads"] = [d.to_json() for d in tdiags]
        all_diags += tdiags
        unknown = core_flags.unknown_env_flags()
        if unknown:
            print(f"  note: unrecognized FLAGS_* env vars: {unknown}")
    errors = [d for d in all_diags if d.severity == "error"]
    print(f"total: {len(all_diags)} diagnostic(s), {len(errors)} error(s)")
    report["schema_version"] = SCHEMA_VERSION
    report["rule_index"] = _rule_index(all_diags)
    report["total_diagnostics"] = len(all_diags)
    report["errors"] = len(errors)
    return (1 if errors else 0), report


# ---------------------------------------------------------------------------
# --matrix: the tier-flag composition gate
# ---------------------------------------------------------------------------

# The matrix's step traces are cached by the composed-plan hash
# (pass_check.composed_plan_hash over the plan-only pipeline build):
# combos whose pipelines compose the same StepPlan trace/compile once.
# cp_nested_ring and pallas_conv live inside the loss function, not the
# pipeline, so they hash equal by construction (their components are
# checked separately below).


def _matrix_micro_step(remat: bool):
    """A tiny 2-block GPT TrainStep on the dp=2 x sharding=2 x mp=2
    hybrid mesh — every axis the composed tiers splice into, at shapes
    that trace in well under a second."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.topology import (create_hybrid_mesh,
                                                 set_hybrid_mesh)
    from paddle_tpu.framework.functional import functional_call
    from paddle_tpu.framework.sharded import make_sharded_train_step
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash_attention=False, recompute=bool(remat))
    model = GPTForCausalLM(cfg)
    mesh = create_hybrid_mesh(dp=2, sharding=2, mp=2)
    set_hybrid_mesh(mesh)

    def loss_fn(m, p, b):
        ids, labels = b
        return functional_call(m, p, ids, labels, training=True)

    ts = make_sharded_train_step(model, AdamW(1e-3), loss_fn, mesh=mesh)
    ids = jnp.zeros((4, 16), jnp.int32)
    return ts, (ids, ids)


def _matrix_step_diags(remat: bool, with_hlo: bool = True):
    """Build + trace the micro TrainStep under the current flags and run
    the full plan verification — and, with ``with_hlo``, AOT-compile the
    same step and run the X-rules over what XLA actually built; returns
    (diags, info)."""
    import time
    from paddle_tpu.analysis import hlo_check, plan_check
    from paddle_tpu.distributed.topology import set_hybrid_mesh
    try:
        ts, batch = _matrix_micro_step(remat)
        closed, donate = ts.trace_step(batch)
        diags = plan_check.check_plan(ts.plan, closed,
                                      donate_argnums=donate,
                                      where="matrix.step")
        info = {"eqns": len(closed.jaxpr.eqns),
                "plan": ts.plan.to_json()}
        if with_hlo:
            t0 = time.perf_counter()
            compiled, donated = ts.compile_step(batch)
            facts = hlo_check.collect_hlo_facts(compiled)
            diags += hlo_check.check_hlo(ts.plan, facts,
                                         donated_leaves=donated,
                                         where="matrix.step.hlo")
            info["hlo"] = dict(facts.to_json(),
                               verify_ms=round(
                                   (time.perf_counter() - t0) * 1e3, 1))
    finally:
        set_hybrid_mesh(None)
    return diags, info


def _matrix_sp_pair_diags():
    """The decomposed TP/SP pair traced fwd+grad on an mp-only mesh (the
    shape the legacy-jax gate admits), with the comm registry recording —
    the declared-vs-actual ppermute cross-check (S001/S002) on the real
    decomposed path, plus the C-rule accounting of each recorded spec and
    the production-shape hop plans."""
    import numpy as np
    from jax.sharding import Mesh
    from paddle_tpu.analysis import comm_check, plan_check
    from paddle_tpu.distributed import overlap

    if jax.device_count() < 2:
        return [], {"skipped": "needs >= 2 devices"}
    n = 8 if jax.device_count() >= 8 else 2
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(1, 1, 1, 1, n),
                ("pp", "dp", "sharding", "sep", "mp"))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8 * n, 16)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)

    def loss(x, w1, w2):
        h = overlap.allgather_matmul(x, w1, mesh=mesh, chunks=1)
        y = overlap.matmul_reduce_scatter(jax.nn.gelu(h), w2, mesh=mesh,
                                          chunks=1)
        return jnp.sum(y ** 2)

    with comm_check.recording() as rec:
        closed = jax.make_jaxpr(jax.value_and_grad(loss, argnums=(1, 2)))(
            x, w1, w2)
    plan = plan_check.StepPlan(
        flags={"comm_overlap": "tp"},
        mesh_axes={str(a): int(mesh.shape[a]) for a in mesh.axis_names},
        nodes=[plan_check.PlanNode("sp_pair", reads=("x", "w1", "w2"),
                                   writes=("loss", "grads"))],
        comm_specs=list(rec))
    diags = plan_check.check_plan(plan, closed, where="matrix.sp_pair")
    # production-shape hop plans (GPT-1.3B layer through mp=4)
    for spec in (comm_check.spec_for_allgather_matmul(
                     8, 512, 2048, 2048, 4, 2),
                 comm_check.spec_for_matmul_reduce_scatter(
                     8, 512, 2048, 2048, 4, 2)):
        diags += comm_check.check_comm_spec(spec)
    return diags, {"recorded_specs": len(rec),
                   "eqns": len(closed.jaxpr.eqns)}


def _matrix_multislice_diags(with_hlo: bool = True):
    """The multislice tier's composition check: the hierarchical 2-tier
    TrainStep traced on the 2-slice virtual mesh and verified against its
    declared StepPlan (S/D rules) + the recorded hop plan's C-rule
    errors — the micro step of the main matrix sweep has no 'slice' axis,
    so the tier is exercised here as a component (like the SP pair).
    With ``with_hlo`` the step is also AOT-compiled and X-rule-verified:
    the compiled reduce-scatter / all-reduce / all-gather kinds must all
    be justified by the recorded hierarchical-stage CommSpecs, and no
    DCN-crossing collective may sit in a compiled loop body (X005)."""
    from paddle_tpu.analysis import comm_check, hlo_check, plan_check
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.topology import set_hybrid_mesh

    if jax.device_count() < 4:
        return [], {"skipped": "needs >= 4 devices"}
    try:
        topo, ts, batch = _multislice_micro_step("hierarchical")
        closed, donate = ts.trace_step(batch)
        diags = plan_check.check_plan(ts.plan, closed,
                                      donate_argnums=donate,
                                      where="matrix.multislice")
        for _, spec in ts.plan.comm_specs:
            diags += [d for d in comm_check.check_comm_spec(spec)
                      if d.severity == "error"]
        info = {"eqns": len(closed.jaxpr.eqns),
                "dcn_axes": topo.dcn_axes(),
                "comm_specs": len(ts.plan.comm_specs)}
        if with_hlo:
            compiled, donated = ts.compile_step(batch)
            facts = hlo_check.collect_hlo_facts(compiled)
            diags += hlo_check.check_hlo(ts.plan, facts,
                                         donated_leaves=donated,
                                         where="matrix.multislice.hlo")
            info["hlo"] = facts.to_json()
    finally:
        set_flags({"multislice": "off"})
        set_hybrid_mesh(None)
    return diags, info


def _matrix_cp_ring_diags():
    """Static hop accounting of the ring-CP tier at a long-context shape
    (S=32k over sep=4, GPT-1.3B heads): the arithmetic half of the
    cp_nested_ring composition — the nested-ring trace itself needs the
    pipeline runtime (new-jax dryrun[7])."""
    from paddle_tpu.analysis import comm_check
    spec = comm_check.spec_for_cp_ring(
        b=1, s_local=8192, heads=16, head_dim=128, n=4, itemsize=2)
    return comm_check.check_comm_spec(spec), {
        "hops": spec.hops, "mib_per_hop": round(spec.bytes_per_hop / 2**20,
                                                2)}


def _matrix_conv_diags():
    """The pallas_conv tier's kernel-config checks (P-rules) at its
    default blocks over the byte-dominant ResNet shapes."""
    import numpy as np
    from paddle_tpu.analysis import (check_kernel_spec, spec_for_conv3x3,
                                     spec_for_conv_matmul)
    from paddle_tpu.ops._pallas import conv as pconv
    diags = []
    bf16 = np.dtype("bfloat16")
    for kind, n, h, w, cin, cout, s_ in pconv.RESNET50_TOP3_SHAPES:
        if kind == "conv1x1":
            m = n * ((h + s_ - 1) // s_) * ((w + s_ - 1) // s_)
            bm = pconv._pick_block_m(m, cin, cout, jnp.bfloat16)
            diags += check_kernel_spec(
                spec_for_conv_matmul(m, cin, cout, bm, dtype=bf16))
        else:
            ho = (h + 2 - 3) // s_ + 1
            bh = pconv._pick_block_h(ho, n, h, w, cin, cout, s_,
                                     jnp.bfloat16)
            diags += check_kernel_spec(
                spec_for_conv3x3(n, h, w, cin, cout, bh, s_, dtype=bf16))
    return diags, {"shapes": len(pconv.RESNET50_TOP3_SHAPES)}


def run_dryruns():
    """The ten multichip dryrun scenarios (__graft_entry__._dryrun_base)
    in a subprocess on the 8-device virtual mesh. Needs the maintained
    jax.shard_map API; on legacy jax this reports skipped — the driver
    environment runs them for real."""
    if not hasattr(jax, "shard_map"):
        return {"skipped": "legacy jax (no jax.shard_map); the dryrun "
                           "scenarios only trace in the driver env",
                "ok": True, "scenarios": []}
    env = dict(os.environ)
    env["_GRAFT_DRYRUN_NO_ESCALATE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    code = (f"import sys; sys.path.insert(0, {REPO!r}); "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import __graft_entry__ as g; g.dryrun_multichip(8)")
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          capture_output=True, text=True)
    import re
    scenarios = sorted(set(
        int(m) for m in re.findall(r"dryrun_multichip\[(\d+)\]",
                                   proc.stdout)))
    ok = proc.returncode == 0 and len(scenarios) >= 10
    out = {"ok": ok, "returncode": proc.returncode, "scenarios": scenarios}
    if not ok:
        out["tail"] = (proc.stdout + proc.stderr)[-2000:]
    return out


def run_matrix(min_severity="info", json_mode=False, with_dryrun=True,
               combos=None, with_hlo=True):
    """Enumerate the tier-flag combinations, verify each composition —
    including the compiled-HLO X-rule pass per trace-distinct step,
    unless ``with_hlo=False`` — and (optionally) run the ten dryrun
    scenarios. Exits nonzero on any error-severity diagnostic or dryrun
    failure."""
    if json_mode:
        import contextlib
        with contextlib.redirect_stdout(sys.stderr):
            rc, report = _run_matrix_impl(min_severity, with_dryrun, combos,
                                          with_hlo)
        print(json.dumps(report, indent=2))
        return rc
    rc, _ = _run_matrix_impl(min_severity, with_dryrun, combos, with_hlo)
    return rc


def _run_matrix_impl(min_severity="info", with_dryrun=True, combos=None,
                     with_hlo=True):
    import tools.hbm_budget as hbm_budget
    from paddle_tpu.analysis import pass_check, plan_check
    from paddle_tpu.core import flags as core_flags
    from paddle_tpu.framework import step_pipeline
    from paddle_tpu.ops._pallas import conv as _pconv  # registers the flag
    del _pconv

    tier_names = [n for n, _ in plan_check.TIER_FLAGS]
    prev = {n: core_flags.flag(n) for n in tier_names
            if n in core_flags.get_flags()}
    # every combo — caller-supplied included — through the one
    # normalization entry point (legacy 5-flag dicts warn once there)
    combos = list(plan_check.iter_tier_combos()) if combos is None \
        else list(combos)
    combos = [plan_check.normalize_combo(c) for c in combos]
    step_cache = {}
    component_cache = {}
    report = {"combos": [], "errors": 0,
              "passes": {
                  "order": [p.contract.name for p in step_pipeline.PIPELINE],
                  "contracts": {
                      p.contract.name: pass_check.contract_hash(p.contract)
                      for p in step_pipeline.PIPELINE}}}
    n_errors = 0
    all_diags = []
    try:
        for combo in combos:
            core_flags.set_flags({
                "offload_optimizer": combo["offload_optimizer"],
                "comm_overlap": combo["comm_overlap"],
                "multislice": combo["multislice"],
                "cp_nested_ring": combo["cp_nested_ring"],
                "pallas_conv": combo["pallas_conv"],
            })
            diags = []
            entry = {"flags": dict(combo)}
            # (a0) the combo composed plan-only through the pass pipeline:
            # the G-rule gate, and the composed-plan hash that keys the
            # step trace cache + the CI composition diff
            pbuild = step_pipeline.compose(step_pipeline.plan_only_build(combo))
            diags += pbuild.diagnostics
            plan_hash = pass_check.composed_plan_hash(pbuild.plan)
            entry["passes"] = {
                "order": [c.name for c in pbuild.contracts],
                "plan_hash": plan_hash}
            # (a) the composed StepPlan, traced + verified (cached by the
            # composed-plan hash: combos whose pipelines emit the same
            # plan share one trace; cp/pallas_conv don't enter the
            # pipeline — their components are checked below)
            if plan_hash not in step_cache:
                step_cache[plan_hash] = _matrix_step_diags(
                    combo["remat"], with_hlo=with_hlo)
            sdiags, sinfo = step_cache[plan_hash]
            diags += sdiags
            entry["step"] = {"eqns": sinfo.get("eqns")}
            if "hlo" in sinfo:
                entry["step"]["hlo"] = sinfo["hlo"]
            # (b) tier components the micro step cannot carry
            if combo["comm_overlap"] != "off":
                if "sp" not in component_cache:
                    component_cache["sp"] = _matrix_sp_pair_diags()
                diags += component_cache["sp"][0]
            if combo["multislice"] != "off":
                # the micro step's mesh has no 'slice' axis (the tier is
                # inert there by design); the 2-slice composition is
                # checked once as a component
                if "multislice" not in component_cache:
                    component_cache["multislice"] = \
                        _matrix_multislice_diags(with_hlo=with_hlo)
                diags += component_cache["multislice"][0]
            if combo["cp_nested_ring"]:
                if "cp" not in component_cache:
                    component_cache["cp"] = _matrix_cp_ring_diags()
                diags += component_cache["cp"][0]
            if combo["pallas_conv"]:
                if "conv" not in component_cache:
                    component_cache["conv"] = _matrix_conv_diags()
                diags += component_cache["conv"][0]
            # (c) capacity: the flagship config this composition is held
            # to (full-depth GPT-1.3B when offloaded, L=12 otherwise)
            cap = hbm_budget.tier_plan(
                offload=combo["offload_optimizer"],
                remat=bool(combo["remat"]))
            diags += plan_check.check_capacity(cap, where="matrix.hbm")
            entry["hbm"] = {"fits": cap["fits"],
                            "device_gb": cap["device_gb"],
                            "layers": cap["config"]["layers"],
                            "batch": cap["config"]["batch"]}
            errors = [d for d in diags if d.severity == "error"]
            n_errors += len(errors)
            all_diags += diags
            entry["diagnostics"] = [d.to_json() for d in diags]
            entry["errors"] = len(errors)
            report["combos"].append(entry)
            tag = " ".join(f"{k}={combo.get(k, 'off')}"
                           for k in tier_names)
            print(f"== matrix {tag}: {len(diags)} diagnostic(s), "
                  f"{len(errors)} error(s)")
            for d in diags:
                if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
                    print("  " + d.format())
    finally:
        core_flags.set_flags(prev)
    if with_dryrun:
        dry = run_dryruns()
        report["dryrun"] = dry
        if dry.get("skipped"):
            print(f"== dryrun scenarios: SKIPPED ({dry['skipped']})")
        else:
            print(f"== dryrun scenarios: {dry['scenarios']} "
                  f"{'ok' if dry['ok'] else 'FAILED'}")
            if not dry["ok"]:
                n_errors += 1
                print(dry.get("tail", ""))
    report["schema_version"] = SCHEMA_VERSION
    report["rule_index"] = _rule_index(all_diags)
    report["errors"] = n_errors
    print(f"matrix total: {len(report['combos'])} combination(s), "
          f"{n_errors} error(s)")
    return (1 if n_errors else 0), report


# ---------------------------------------------------------------------------
# --passes: the step-compiler pass-pipeline verifier (G rules)
# ---------------------------------------------------------------------------

def _passes_selftests():
    """Seeded bad compositions: G001 (a pass ordered before its
    provider), G002 (conflicting buffer ownership with no declared
    handoff), G004 (an undeclared order-sensitive pair) must each fire —
    the gate that proves each rule still detects its hazard class."""
    import dataclasses
    from paddle_tpu.analysis.jaxpr_lint import Diagnostic
    from paddle_tpu.analysis.pass_check import PassContract
    from paddle_tpu.framework import step_pipeline as sp

    pipe = {p.contract.name: p for p in sp.PIPELINE}
    combo = {"offload_optimizer": "moments", "comm_overlap": "tp_zero",
             "multislice": "off", "cp_nested_ring": False,
             "pallas_conv": 0, "remat": False}

    def fired(rule, order, **kw):
        b = sp.plan_only_build(combo, **kw)
        sp.compose(b, order=order)
        return any(d.rule == rule for d in b.diagnostics)

    class _Rogue(sp.StepPass):
        # writes/donates base_grad's params with no declared handoff
        contract = PassContract(
            name="rogue", requires=("grads",), provides=("rogue",),
            terminal=("rogue",), plan_writes=("params",),
            plan_donates=("params",))

    class _NoEdgeSentinel(sp.HealthSentinelPass):
        # the genuinely order-sensitive sentinel<->offload pair with its
        # declared edge stripped
        contract = dataclasses.replace(sp.HealthSentinelPass.contract,
                                       order_after=())

    results = {
        "G001": fired("G001", [pipe["offload_stream"], pipe["base_grad"]]),
        "G002": fired("G002", [pipe["base_grad"], _Rogue(),
                               pipe["offload_stream"]]),
        "G004": fired("G004",
                      [_NoEdgeSentinel() if isinstance(
                          p, sp.HealthSentinelPass) else p
                       for p in sp.PIPELINE],
                      health_sentinel=True),
    }
    diags = []
    for rule, ok in sorted(results.items()):
        if not ok:
            diags.append(Diagnostic(
                rule=rule, name="selftest-missing", severity="error",
                message=f"self-test: {rule} did not fire on its seeded "
                        "bad composition",
                where="passes.selftest"))
    return results, diags


def run_passes(min_severity="info", json_mode=False):
    """The pass-pipeline G-rule gate standalone: the declared pipeline
    (ordered pass list + per-pass contract hashes), every tier combo in
    BOTH sentinel arms composed plan-only and G-rule-verified (256
    compositions, incl. sentinel x offload), and the seeded per-rule
    self-tests."""
    if json_mode:
        import contextlib
        with contextlib.redirect_stdout(sys.stderr):
            rc, report = _run_passes_impl(min_severity)
        print(json.dumps(report, indent=2))
        return rc
    rc, _ = _run_passes_impl(min_severity)
    return rc


def _run_passes_impl(min_severity="info"):
    from paddle_tpu.analysis import pass_check, plan_check
    from paddle_tpu.framework import step_pipeline as sp
    all_diags = []
    report = {
        "schema_version": SCHEMA_VERSION,
        "passes": {
            "order": [p.contract.name for p in sp.PIPELINE],
            "contracts": {
                p.contract.name: pass_check.contract_hash(p.contract)
                for p in sp.PIPELINE}},
        "combos": [],
    }
    print("== pass pipeline: "
          + " -> ".join(report["passes"]["order"]))
    for name, h in report["passes"]["contracts"].items():
        print(f"  contract {name}: {h}")
    n_hashes = set()
    for combo in plan_check.iter_tier_combos():
        for sentinel in (False, True):
            b = sp.plan_only_build(combo, health_sentinel=sentinel)
            sp.compose(b)
            h = pass_check.composed_plan_hash(b.plan)
            n_hashes.add(h)
            errors = [d for d in b.diagnostics if d.severity == "error"]
            report["combos"].append({
                "flags": dict(combo, health_sentinel=sentinel),
                "order": [c.name for c in b.contracts],
                "plan_hash": h,
                "diagnostics": [d.to_json() for d in b.diagnostics],
                "errors": len(errors)})
            all_diags += b.diagnostics
            for d in b.diagnostics:
                if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
                    print("  " + d.format())
    print(f"== {len(report['combos'])} compositions "
          f"(incl. sentinel arms), {len(n_hashes)} distinct plan hash(es)")
    fired, st_diags = _passes_selftests()
    print("== passes self-tests (each rule must fire on its seeded "
          "bad composition)")
    for rule, ok in sorted(fired.items()):
        print(f"  {rule}: {'fires' if ok else 'MISSING'}")
    report["selftests"] = fired
    all_diags += st_diags
    errors = [d for d in all_diags if d.severity == "error"]
    report["rule_index"] = _rule_index(all_diags)
    report["total_diagnostics"] = len(all_diags)
    report["errors"] = len(errors)
    print(f"passes total: {len(all_diags)} diagnostic(s), "
          f"{len(errors)} error(s)")
    return (1 if errors else 0), report


# ---------------------------------------------------------------------------
# --threads: the host-concurrency verifier (T rules)
# ---------------------------------------------------------------------------

# Seeded-positive fixtures: one per T rule, each MUST fire — the gate
# that proves the rule still detects the hazard class it was built for.
THREADS_FIXTURES = {
    "T001": ("t001.py", """
import threading
class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def inc(self):
        with self._lock:
            self.n += 1
    def reset(self):
        self.n = 0
"""),
    "T002": ("t002.py", """
import threading
class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def ab(self):
        with self._a:
            with self._b:
                pass
    def ba(self):
        with self._b:
            with self._a:
                pass
"""),
    "T003": ("t003.py", """
import os
import threading
class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self.f = None
    def write(self):
        with self._lock:
            os.fsync(self.f.fileno())
"""),
    "T004": ("t004.py", """
import threading
class Spawner:
    def spawn(self):
        t = threading.Thread(target=self._work)
        t.start()
        self._t = t
    def arm(self):
        self._timer = threading.Timer(1.0, self._work)
        self._timer.start()
    def _work(self):
        pass
"""),
    "T005": ("serving/engine.py", """
class Engine:
    def _finish(self, seq):
        self.detokenizer(seq)
        self.journal.done(seq.rid, [])
"""),
}


def _threads_selftests():
    """Run every fixture through the analyzer; a rule that does NOT fire
    on its seeded positive is itself an error."""
    from paddle_tpu.analysis import concurrency_check
    from paddle_tpu.analysis.jaxpr_lint import Diagnostic
    diags, fired = [], {}
    for rule, (relpath, src) in sorted(THREADS_FIXTURES.items()):
        got = concurrency_check.check_source(src, relpath)
        fired[rule] = any(d.rule == rule for d in got)
        if not fired[rule]:
            diags.append(Diagnostic(
                rule=rule, name="selftest-missing", severity="error",
                message=f"self-test: {rule} did not fire on its seeded "
                        f"positive fixture {relpath}",
                where="threads.selftest"))
    return fired, diags


def run_threads(min_severity="info", json_mode=False):
    """The T-rule pass standalone: the seeded per-rule self-tests (every
    rule must fire on its positive fixture) + the whole-repo sweep
    (which must be clean) + the repo-wide static lock acquisition graph
    cycle check."""
    if json_mode:
        import contextlib
        with contextlib.redirect_stdout(sys.stderr):
            rc, report = _run_threads_impl(min_severity)
        print(json.dumps(report, indent=2))
        return rc
    rc, _ = _run_threads_impl(min_severity)
    return rc


def _run_threads_impl(min_severity="info"):
    from paddle_tpu.analysis import concurrency_check
    all_diags = []
    report = {"schema_version": SCHEMA_VERSION}
    fired, st_diags = _threads_selftests()
    print("== threads self-tests (each rule must fire on its fixture)")
    for rule, ok in sorted(fired.items()):
        print(f"  {rule}: {'fires' if ok else 'MISSING'}")
    report["selftests"] = fired
    all_diags += st_diags
    repo_diags = concurrency_check.check_tree(REPO)
    print(f"== repo concurrency lint (T rules over paddle_tpu/ + tools/ "
          f"+ examples/): {len(repo_diags)} diagnostic(s)")
    for d in repo_diags:
        if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
            print("  " + d.format())
    report["repo"] = [d.to_json() for d in repo_diags]
    all_diags += repo_diags
    # the cross-module static acquisition graph: cycles anywhere in the
    # tree, including across files one module's T002 pass cannot see
    mods = concurrency_check.collect_module_facts(REPO)
    edges = concurrency_check.acquisition_graph(mods)
    cycles = concurrency_check.find_lock_cycles(edges)
    cycles = [c for c in cycles if len(c) >= 3]
    print(f"== static lock graph: {len(edges)} edge(s), "
          f"{len(cycles)} cycle(s)")
    report["lock_graph"] = {
        "edges": len(edges), "cycles": [" -> ".join(c) for c in cycles]}
    if cycles:
        from paddle_tpu.analysis.jaxpr_lint import Diagnostic
        for c in cycles:
            all_diags.append(Diagnostic(
                rule="T002", name="lock-order-inversion", severity="error",
                message="cross-module lock acquisition cycle "
                        + " -> ".join(c),
                where="threads.graph"))
    errors = [d for d in all_diags if d.severity == "error"]
    report["rule_index"] = _rule_index(all_diags)
    report["total_diagnostics"] = len(all_diags)
    report["errors"] = len(errors)
    print(f"threads total: {len(all_diags)} diagnostic(s), "
          f"{len(errors)} error(s)")
    return (1 if errors else 0), report


# ---------------------------------------------------------------------------
# --hlo: the compiled-HLO verifier, standalone
# ---------------------------------------------------------------------------

def run_hlo(min_severity="info", json_mode=False):
    """AOT-compile the representative composed steps and run the X-rules
    (analysis/hlo_check) over what XLA actually built: the hybrid-mesh
    micro TrainStep, the serving decode executable, the 2-slice
    multislice step, plus a seeded undeclared-collective self-test (X001
    must fire on GSPMD resharding nothing declared — the rule exists to
    catch exactly that)."""
    if json_mode:
        import contextlib
        with contextlib.redirect_stdout(sys.stderr):
            rc, report = _run_hlo_impl(min_severity)
        print(json.dumps(report, indent=2))
        return rc
    rc, _ = _run_hlo_impl(min_severity)
    return rc


def _hlo_seeded_x001_selftest():
    """X001 must fire on a compiled resharding all-gather nothing
    declared (replicated params, an intermediate constrained onto a mesh
    axis: GSPMD gathers it back — the sneaked-in collective)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.analysis import hlo_check, plan_check

    n = jax.device_count()
    mesh = Mesh(np.asarray(jax.devices()).reshape(n), ("dp",))
    repl = NamedSharding(mesh, P())

    def sneaky(w, x):
        h = jax.lax.with_sharding_constraint(
            x @ w, NamedSharding(mesh, P(None, "dp")))
        return jnp.tanh(h) @ w

    compiled = jax.jit(sneaky, in_shardings=(repl, repl),
                       out_shardings=repl).lower(
        jnp.ones((16, 16)), jnp.ones((8, 16))).compile()
    plan = plan_check.StepPlan(mesh_axes={"dp": n})
    diags = hlo_check.check_hlo(plan, compiled, where="hlo.selftest")
    return [d for d in diags if d.rule == "X001"]


def _run_hlo_impl(min_severity="info"):
    from paddle_tpu.analysis import hlo_check
    from paddle_tpu.analysis.jaxpr_lint import Diagnostic
    all_diags = []
    report = {"targets": {}, "schema_version": SCHEMA_VERSION}

    def verify(name, compiled, plan, donated):
        import time
        t0 = time.perf_counter()
        facts = hlo_check.collect_hlo_facts(compiled)
        diags = hlo_check.check_hlo(plan, facts, donated_leaves=donated,
                                    where=f"hlo.{name}")
        ms = round((time.perf_counter() - t0) * 1e3, 1)
        print(f"== hlo {name}: {facts.to_json()}, verify {ms} ms, "
              f"{len(diags)} diagnostic(s)")
        for d in diags:
            if _SEV_RANK[d.severity] >= _SEV_RANK[min_severity]:
                print("  " + d.format())
        report["targets"][name] = dict(facts.to_json(), verify_ms=ms,
                                       diagnostics=[d.to_json()
                                                    for d in diags])
        all_diags.extend(diags)

    # (a) the hybrid-mesh micro TrainStep (the --matrix micro model)
    from paddle_tpu.distributed.topology import set_hybrid_mesh
    try:
        ts, batch = _matrix_micro_step(False)
        ts.trace_step(batch)  # fills plan.comm_specs
        compiled, donated = ts.compile_step(batch)
        verify("train_step", compiled, ts.plan, donated)
    finally:
        set_hybrid_mesh(None)
    # (b) the serving decode executable at its smallest bucket
    import paddle_tpu as paddle
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models.gpt import GPTForCausalLM, gpt_tiny
    paddle.seed(0)
    cfg = gpt_tiny(vocab_size=128, hidden_size=48, num_layers=2,
                   num_heads=4, max_position_embeddings=64)
    eng = ServingEngine(GPTForCausalLM(cfg), block_size=4, num_blocks=32,
                        max_batch=4)
    compiled, donated = eng.compile_decode()
    verify("serving_decode", compiled, eng.plan, donated)
    # (c) the 2-slice multislice step (hierarchical reduction compiled)
    if jax.device_count() >= 4:
        from paddle_tpu.core.flags import set_flags
        try:
            topo, ms_ts, ms_batch = _multislice_micro_step("hierarchical")
            ms_ts.trace_step(ms_batch)
            compiled, donated = ms_ts.compile_step(ms_batch)
            verify("multislice_step", compiled, ms_ts.plan, donated)
        finally:
            set_flags({"multislice": "off"})
            set_hybrid_mesh(None)
    # (d) X001 self-test: the seeded undeclared collective must fire
    fired = _hlo_seeded_x001_selftest()
    print(f"== hlo X001 on the seeded undeclared resharding gather: "
          f"{'fires' if fired else 'MISSING'}")
    report["x001_selftest_fires"] = bool(fired)
    if not fired:
        all_diags.append(Diagnostic(
            rule="X001", name="undeclared-compiled-collective",
            severity="error",
            message="self-test: X001 did not fire on a compiled "
                    "resharding all-gather with nothing declared",
            where="hlo.selftest"))
    errors = [d for d in all_diags if d.severity == "error"]
    report["rule_index"] = _rule_index(all_diags)
    report["errors"] = len(errors)
    print(f"hlo total: {len(all_diags)} diagnostic(s), "
          f"{len(errors)} error(s)")
    return (1 if errors else 0), report


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", choices=sorted(MODELS), action="append",
                   help="model graph(s) to lint (repeatable)")
    p.add_argument("--all", action="store_true",
                   help="lint every model + pallas kernel configs + repo AST")
    p.add_argument("--matrix", action="store_true",
                   help="verify every tier-flag combination's composed "
                        "StepPlan (+ compiled-HLO X-rules) + the ten "
                        "dryrun scenarios")
    p.add_argument("--hlo", action="store_true",
                   help="compiled-HLO verifier (X-rules) over the "
                        "representative composed steps + the X001 "
                        "seeded self-test")
    p.add_argument("--threads", action="store_true",
                   help="host-concurrency verifier (T-rules): per-rule "
                        "seeded self-tests + the repo sweep + the "
                        "static lock-order graph")
    p.add_argument("--passes", action="store_true",
                   help="step-compiler pass-pipeline verifier (G-rules): "
                        "contract hashes, every tier combo composed "
                        "plan-only, + seeded G001/G002/G004 self-tests")
    p.add_argument("--no-dryrun", action="store_true",
                   help="with --matrix: skip the multichip dryrun scenarios")
    p.add_argument("--no-hlo", action="store_true",
                   help="with --matrix: skip the compiled-HLO X-rule pass")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout (narration "
                        "moves to stderr)")
    p.add_argument("--min-severity", choices=["info", "warning", "error"],
                   default="info", help="only print findings at or above")
    a = p.parse_args(argv)
    if a.matrix:
        return run_matrix(min_severity=a.min_severity, json_mode=a.json,
                          with_dryrun=not a.no_dryrun,
                          with_hlo=not a.no_hlo)
    if a.hlo:
        return run_hlo(min_severity=a.min_severity, json_mode=a.json)
    if a.passes:
        return run_passes(min_severity=a.min_severity, json_mode=a.json)
    if a.threads:
        return run_threads(min_severity=a.min_severity, json_mode=a.json)
    if a.all:
        models = sorted(MODELS)
    else:
        models = a.model or ["bert"]
    return run(models, with_kernels=a.all, with_repo=a.all,
               min_severity=a.min_severity, json_mode=a.json)


if __name__ == "__main__":
    sys.exit(main())
