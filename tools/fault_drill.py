#!/usr/bin/env python
"""Fault-tolerance drill CLI: train -> kill -> relaunch -> resume -> measure.

    python tools/fault_drill.py --quick            # tier-1-safe: tiny model,
                                                   # 2 kills, <60s, CPU
    python tools/fault_drill.py --quick --health   # + one inject_nan and one
                                                   # inject_hang chained in,
                                                   # same parity gate, <90s
    python tools/fault_drill.py --steps 40 --kills 3 --seed 11 --size small
    python tools/fault_drill.py --quick --json     # report JSON on stdout
    python tools/fault_drill.py --quick --out REPORT.json

Runs the drill trainer under the elastic manager with a deterministic
seed-driven FaultPlan (SIGKILL mid-step, SIGKILL mid-checkpoint-write,
SIGTERM preemption), then an uninterrupted reference over the same steps,
and reports:

- bitwise loss parity fault-run vs reference (the recovery-completeness
  proof: params + optimizer moments + PRNG + batch cursor all resumed);
- goodput = useful_step_time / wall_time_including_restart, restart
  count, lost (re-executed) steps, checkpoint save/restore durations.

Exits nonzero when the drill fails to finish or parity breaks.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--quick", action="store_true",
                   help="tier-1-safe drill: tiny model, 2 kills "
                        "(mid-step + mid-checkpoint-write)")
    p.add_argument("--health", action="store_true",
                   help="chain one inject_nan + one inject_hang into the "
                        "drill with the guarded trainer (sentinel + "
                        "watchdog + Guardian) armed; the parity gate "
                        "compares against a clean run handed the same "
                        "poisoned-batch skip set")
    p.add_argument("--workdir", default=None,
                   help="drill scratch dir (default: a fresh temp dir)")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--ckpt-every", type=int, default=None)
    p.add_argument("--kills", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--size", choices=("quick", "small"), default=None)
    p.add_argument("--kinds", default=None,
                   help="comma list from mid_step,mid_ckpt_write,sigterm")
    p.add_argument("--reference", choices=("inline", "subprocess"),
                   default="inline")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--out", default=None, help="also write the report here")
    args = p.parse_args(argv)

    from paddle_tpu.fault import drill

    cfg = drill.quick_health_config() if args.health else \
        drill.quick_config()
    if not args.quick and not args.health and args.steps is None:
        cfg.update(total_steps=24, ckpt_every=4, n_kills=3,
                   kinds=("mid_step", "mid_ckpt_write", "sigterm"))
    for key, val in (("total_steps", args.steps),
                     ("ckpt_every", args.ckpt_every),
                     ("n_kills", args.kills), ("seed", args.seed),
                     ("size", args.size)):
        if val is not None:
            cfg[key] = val
    if args.kinds:
        cfg["kinds"] = tuple(k.strip() for k in args.kinds.split(","))

    workdir = args.workdir or tempfile.mkdtemp(prefix="fault_drill_")
    report = drill.run_drill(workdir, reference=args.reference, **cfg)
    report["workdir"] = workdir

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(drill.report_summary(report))
        print(json.dumps({"metric": "fault_drill",
                          "goodput": report.get("goodput_record", {})
                          .get("goodput"),
                          "parity": report.get("parity", {})
                          .get("bitwise_equal")}))

    ok = (report.get("rc") == 0 and report.get("done")
          and report.get("parity", {}).get("bitwise_equal"))
    if ok and "postmortem" in report:
        # the reconstructed story (recorder files + journals alone) must
        # match the injected plan and cohere with the train log
        ok = bool(report["postmortem"].get("ok"))
    if args.health and ok:
        kinds = [a.get("kind")
                 for a in report.get("health", {}).get("anomalies", [])]
        ok = "nan_loss" in kinds and "hang" in kinds
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
