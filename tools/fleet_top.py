#!/usr/bin/env python
"""fleet-top: live terminal view of a running fleet's telemetry plane.

    python tools/fleet_top.py RUN_DIR                  # refreshing console
    python tools/fleet_top.py RUN_DIR --interval 2
    python tools/fleet_top.py RUN_DIR --once           # one frame, no ANSI
    python tools/fleet_top.py RUN_DIR --once --json    # machine view (CI)
    python tools/fleet_top.py RUN_DIR --once --json --fail-on-alert

Reads the CRC-framed snapshots every worker publishes under
``RUN_DIR/fleet/`` (``FLAGS_fleet_telemetry=on``), merges them with
``observability.live.aggregate`` and evaluates the default SLO rules
(``observability.alerts.default_rules``). Per worker: latest step,
tokens/s over the embedded history window, request outcomes, staleness
(fresh/slow/exited/dead) and snapshot age; fleet footer: size, live
goodput, tokens/s, tightest KV pool, worst decode p99, step-lag spread,
and every currently-firing alert.

Exit code: 0 normally; 1 with ``--fail-on-alert`` when any alert fires;
2 when RUN_DIR holds no readable snapshots at all.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt(v, spec="{:.3g}", none="-"):
    return none if v is None else spec.format(v)


def _rate(history, key):
    from paddle_tpu.observability import live
    return live._window_rate(history, key)


def render(view, alerts_active, color=True):
    """One frame of the console view as a list of lines."""
    dim = "\033[2m" if color else ""
    bold = "\033[1m" if color else ""
    red = "\033[31m" if color else ""
    yellow = "\033[33m" if color else ""
    reset = "\033[0m" if color else ""
    status_color = {"fresh": "", "slow": yellow, "dead": red,
                    "exited": dim}
    d = view["derived"]
    lines = [
        f"{bold}fleet-top{reset}  {view['run_dir']}  "
        f"workers={d['fleet_size']} live={d['live_workers']} "
        f"dead={d['dead_workers']}  "
        f"goodput={_fmt(d['live_goodput'], '{:.3f}')}  "
        f"tok/s={_fmt(d['fleet_tokens_per_s'], '{:.1f}')}  "
        f"free_frac={_fmt(d['min_free_block_frac'], '{:.3f}')}  "
        f"p99_decode={_fmt(d['max_p99_decode_ms'], '{:.1f}ms')}  "
        f"lag={d['step_lag_spread']}",
        f"{dim}{'worker':<16}{'inc':>4}{'pid':>8}{'status':>8}"
        f"{'step':>8}{'tok/s':>9}{'ok':>6}{'shed':>6}{'rej':>5}"
        f"{'age_s':>8}{reset}",
    ]
    for key in sorted(view["workers"]):
        w = view["workers"][key]
        sig = w["signals"]
        c = status_color.get(w["status"], "")
        lines.append(
            f"{c}{key:<16}{w['incarnation']:>4}{w['pid']:>8}"
            f"{w['status']:>8}{_fmt(w['step'], '{:d}'):>8}"
            f"{_fmt(_rate(w['history'], 'tokens'), '{:.1f}'):>9}"
            f"{_fmt(w['totals'].get('serving.requests_completed'), '{:.0f}'):>6}"
            f"{_fmt(w['totals'].get('serving.shed'), '{:.0f}'):>6}"
            f"{_fmt(w['totals'].get('serving.rejected'), '{:.0f}'):>5}"
            f"{w['age_s']:>8.1f}{reset}")
    for a in alerts_active:
        c = red if a.severity == "error" else yellow
        lines.append(f"{c}ALERT [{a.rule_id}/{a.rule}] {a.message}{reset}")
    if not alerts_active:
        lines.append(f"{dim}no alerts firing{reset}")
    return lines


def one_frame(run_dir, engine, ttl_s=None, now=None):
    from paddle_tpu.observability import live
    view = live.aggregate(run_dir, now=now, ttl_s=ttl_s)
    engine.evaluate(view, now=now)
    return view, engine.active()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("run_dir", help="run directory (or its fleet/ subdir)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds (live mode)")
    p.add_argument("--ttl", type=float, default=None,
                   help="staleness TTL override in seconds (default: "
                        "2x each worker's own export interval)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="arm the p99-decode-deadline rule against this "
                        "bound")
    p.add_argument("--once", action="store_true",
                   help="render a single frame and exit")
    p.add_argument("--json", action="store_true",
                   help="with --once: emit the machine-readable view")
    p.add_argument("--fail-on-alert", action="store_true",
                   help="exit 1 when any alert is firing (CI gate)")
    args = p.parse_args(argv)

    from paddle_tpu.observability import alerts
    engine = alerts.AlertEngine(
        alerts.default_rules(deadline_ms=args.deadline_ms),
        emit_mode="off")  # the console IS the output channel here

    if args.once:
        view, active = one_frame(args.run_dir, engine, ttl_s=args.ttl)
        if not view["workers"]:
            print(f"no readable fleet snapshots under {args.run_dir}",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(
                {"view": view, "alerts": [a.to_json() for a in active]},
                sort_keys=True, default=str))
        else:
            print("\n".join(render(view, active, color=False)))
        return 1 if (args.fail_on_alert and active) else 0

    try:
        while True:
            view, active = one_frame(args.run_dir, engine, ttl_s=args.ttl)
            frame = render(view, active,
                           color=sys.stdout.isatty())
            sys.stdout.write("\033[2J\033[H" if sys.stdout.isatty()
                             else "")
            sys.stdout.write("\n".join(frame) + "\n")
            sys.stdout.flush()
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
