"""Profile the ResNet-50 train step by HLO category, fused vs plain path.

Usage: python tools/profile_resnet.py [fused|plain] [top_n]
"""
import functools
import os
import shutil
import sys
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as paddle
from paddle_tpu.core import flags as _flags
from paddle_tpu.framework.functional import (functional_call, get_buffers,
                                             get_params)
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import fused_conv_bn  # ensure flag defined
from paddle_tpu.optimizer import Momentum
from paddle_tpu.vision.models import resnet50
from paddle_tpu.profiler.statistic import device_statistics

mode = sys.argv[1] if len(sys.argv) > 1 else "fused"
top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 18
_flags.set_flags({"fused_conv_bn": 1 if mode == "fused" else 0})

batch, img, steps = 256, 224, 6
paddle.seed(0)
model = resnet50(data_format="NHWC")
model.train()
model.astype(paddle.bfloat16)
opt = Momentum(learning_rate=0.1, momentum=0.9, multi_precision=True)
params = get_params(model)
buffers = get_buffers(model)
opt_state = opt.init(params)


def loss_of(p, buf, x, y):
    out, new_buf = functional_call(model, p, x, buffers=buf, mutable=True,
                                   training=True)
    return F.cross_entropy(out.astype(jnp.float32), y,
                           reduction="mean"), new_buf


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, x, y):
    p, buf, st = state
    (loss, new_buf), grads = jax.value_and_grad(
        loss_of, has_aux=True)(p, buf, x, y)
    new_p, new_st = opt.apply_gradients(p, grads, st, 0.1)
    return loss, (new_p, new_buf, new_st)


rng = np.random.default_rng(0)
x = jnp.asarray(rng.standard_normal((batch, img, img, 3)), jnp.bfloat16)
y = jnp.asarray(rng.integers(0, 1000, (batch,)), jnp.int32)
state = (params, buffers, opt_state)
losses = []
loss, state = step(state, x, y)
losses.append(float(loss))
for _ in range(3):
    loss, state = step(state, x, y)
    losses.append(float(loss))

tracedir = tempfile.mkdtemp(prefix="rn_profile_")
with jax.profiler.trace(tracedir):
    for _ in range(steps):
        loss, state = step(state, x, y)
    float(loss)
st = device_statistics(tracedir, top=top_n)
shutil.rmtree(tracedir, ignore_errors=True)
by_cat, top = st
total = sum(by_cat.values())
print(f"mode={mode}  device total {total/steps:.2f} ms/step   "
      f"losses={['%.4f' % l for l in losses]}")
for cat, ms in sorted(by_cat.items(), key=lambda kv: -kv[1]):
    print(f"  {cat:28s} {ms/steps:8.3f} ms/step")
print("top ops:")
for o in top:
    print(f"  {o['ms']/steps:8.3f} ms  x{o['occurrences']}  "
          f"[{o['category']}] {o['bound_by']:8s} {o['op'][:95]}")
