#!/usr/bin/env python
"""Seeded, deterministic interleaving drills over the repo's three
invariant-critical concurrent objects.

The static T rules (``analysis/concurrency_check.py``) prove lock
*discipline*; this drill proves the *protocols* hold under adversarial
operation orders. A cooperative scheduler runs N worker threads but
grants the run token to exactly one at a time, switching at explicit
yield points in an order drawn from a seeded RNG — every schedule is a
real multi-thread execution (real locks, real fsyncs) that replays
bit-for-bit from its seed.

Three drills, each asserting its object's invariants after every
operation and at the end of every schedule:

- **allocator/prefix-tree** — concurrent sequences match/attach/insert/
  release against one refcounted ``BlockAllocator`` + ``PrefixCache``
  under eviction pressure: ``assert_consistent`` (refcounts >=
  1 + seq_refs, no resident+spilled node), no block leak, no
  double-free, no reserved-block drift.
- **request journal** — concurrent submit/ack writers plus a seeded
  torn-tail crash + replay: ``exactly_once_report`` must come back with
  zero lost and zero duplicated acks across the relaunch.
- **checkpoint manager** — async saves racing ``latest_complete``/
  ``restore`` readers with a seeded snapshot corruption: the reader
  must always land on a validating snapshot (torn-snapshot skip), the
  degraded flag must be observed coherently, and a restored state must
  round-trip bitwise.

``FLAGS_lockcheck`` is armed for the whole run: every instrumented lock
feeds the runtime acquisition-order graph, and the drill finishes with
``check_runtime_order`` — a lock-order inversion witnessed under ANY
schedule fails the drill even though no schedule happened to deadlock.

    python tools/race_drill.py --quick          # 20 seeds, tier-1 speed
    python tools/race_drill.py --seeds 200      # the long soak
    python tools/race_drill.py --drill journal --seeds 50
"""

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


# ---------------------------------------------------------------------------
# The deterministic scheduler
# ---------------------------------------------------------------------------

class ScheduleViolation(AssertionError):
    """An invariant broke under some schedule; the message carries the
    seed so the exact interleaving replays."""


class DrillScheduler:
    """Cooperative single-token scheduler over real threads.

    Workers are callables taking one argument — the scheduler — and must
    call :meth:`step` between operations (the explicit yield points).
    Only the token holder runs; the next holder is drawn from the seeded
    RNG, so the interleaving of *operations* is deterministic while the
    operations themselves execute on genuinely distinct threads against
    real locks."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)
        self.seed = seed
        self._cv = threading.Condition()
        self._current = None        # worker id holding the token
        self._runnable = []         # workers waiting at a yield point
        self._done = set()
        self._errors = []
        self._n = 0

    # -- worker side ---------------------------------------------------------

    def step(self):
        """Yield point: hand the token back and wait to be rescheduled."""
        me = threading.current_thread()._drill_id
        with self._cv:
            self._current = None
            self._runnable.append(me)
            self._cv.notify_all()
            while self._current != me:
                self._cv.wait(timeout=30.0)
                if self._current is None and me not in self._runnable:
                    # scheduler abandoned us (another worker errored)
                    raise ScheduleViolation("schedule aborted")

    # -- driver side ---------------------------------------------------------

    def run(self, workers):
        self._n = len(workers)
        threads = []
        for i, fn in enumerate(workers):
            t = threading.Thread(target=self._trampoline, args=(i, fn),
                                 daemon=True, name=f"drill-w{i}")
            t._drill_id = i
            threads.append(t)
        for t in threads:
            t.start()
        while True:
            with self._cv:
                while (len(self._runnable) + len(self._done) < self._n
                        and not self._errors):
                    self._cv.wait(timeout=30.0)
                if self._errors:
                    break
                if len(self._done) == self._n:
                    break
                if not self._runnable:
                    break
                nxt = self._runnable.pop(
                    self.rng.randrange(len(self._runnable)))
                self._current = nxt
                self._cv.notify_all()
                # wait until that worker yields again or finishes
                while self._current == nxt and nxt not in self._done \
                        and not self._errors:
                    self._cv.wait(timeout=30.0)
        for t in threads:
            t.join(timeout=30.0)
        if self._errors:
            raise self._errors[0]

    def _trampoline(self, i, fn):
        # park until first scheduled
        self.step()
        try:
            fn(self)
        except ScheduleViolation:
            raise
        except BaseException as e:
            with self._cv:
                self._errors.append(ScheduleViolation(
                    f"seed {self.seed}, worker {i}: "
                    f"{type(e).__name__}: {e}"))
                self._cv.notify_all()
            return
        with self._cv:
            self._done.add(i)
            self._current = None
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# Drill 1: refcounted allocator + prefix tree
# ---------------------------------------------------------------------------

def _tiny_cache():
    from paddle_tpu.serving.paged_cache import PagedKVCache
    return PagedKVCache(n_layers=1, num_blocks=14, block_size=2,
                        kv_heads=1, head_dim=2)


def _check_tree(cache, tree):
    tree.assert_consistent()
    alloc = cache.allocator
    n_total = alloc.num_blocks - len(alloc._reserved)
    if alloc.n_free + alloc.n_used != n_total:
        raise ScheduleViolation(
            f"block leak: free {alloc.n_free} + used {alloc.n_used} "
            f"!= {n_total}")


def drill_prefix(seed: int) -> dict:
    """Concurrent sequences sharing prompt prefixes: attach/insert/
    release/evict churn over one allocator + trie."""
    from paddle_tpu.serving.prefix_tree import PrefixCache

    cache = _tiny_cache()
    tree = PrefixCache(cache)
    bs = cache.block_size
    # three prompts sharing a 2-block prefix, plus a private one
    base = [7, 3, 9, 1]
    prompts = [np.asarray(base + [11, t], np.int32)
               for t in (0, 1, 2)] + [np.asarray([5, 5, 5, 5, 5],
                                                 np.int32)]
    stats = {"attached": 0, "inserted": 0, "evicted": 0}
    mu = threading.Lock()   # op-granular: ops are atomic, order is fuzzed

    def worker(widx):
        rng = random.Random((seed << 4) + widx)

        def body(sched):
            for _ in range(6):
                sched.step()
                prompt = prompts[rng.randrange(len(prompts))]
                with mu:
                    chain = tree.match(prompt)
                    got = tree.attach(f"w{widx}", chain,
                                      lambda n: cache.allocator.alloc(n))
                    chain = chain[:len(got)]
                    stats["attached"] += len(got)
                    _check_tree(cache, tree)
                sched.step()
                with mu:
                    # cold-prefill the uncovered full blocks privately,
                    # then publish them into the trie (the engine's
                    # insert-after-prefill); blocks the trie refuses
                    # (a racing duplicate insert won the key) stay
                    # private and are freed like a retired sequence's
                    # tail
                    n_full = max(0, (prompt.size - 1) // bs)
                    need = n_full - len(chain)
                    priv = cache.allocator.alloc(need) if need > 0 else []
                    if priv is not None and need > 0:
                        new = tree.insert(prompt, list(got) + priv,
                                          filled_tokens=n_full * bs,
                                          have=len(chain))
                        stats["inserted"] += len(new)
                        # the trie took its own ref on each new node;
                        # our alloc grant doubles as the attachment
                        chain = chain + new
                        consumed = {n.block_id for n in new}
                        leftover = [b for b in priv if b not in consumed]
                        if leftover:
                            cache.allocator.free(leftover)
                    elif priv is None:
                        stats["evicted"] += tree.evict(need)
                    _check_tree(cache, tree)
                sched.step()
                with mu:
                    tree.release(chain)
                    _check_tree(cache, tree)
                if rng.random() < 0.3:
                    sched.step()
                    with mu:
                        stats["evicted"] += tree.evict(1)
                        _check_tree(cache, tree)
        return body

    sched = DrillScheduler(seed)
    sched.run([worker(i) for i in range(3)])
    with mu:
        # drain the cache tier: every block must come home
        tree.evict(cache.allocator.num_blocks, spill=False)
        _check_tree(cache, tree)
        if cache.allocator.n_used != 0:
            raise ScheduleViolation(
                f"seed {seed}: {cache.allocator.n_used} block(s) still "
                "allocated after full release+evict")
    return stats


# ---------------------------------------------------------------------------
# Drill 2: exactly-once request journal
# ---------------------------------------------------------------------------

def drill_journal(seed: int, workdir: str) -> dict:
    """Concurrent submit/ack writers + a seeded torn-tail crash and
    replay: every rid acked exactly once across the relaunch."""
    from paddle_tpu.serving.resilience import RequestJournal

    path = os.path.join(workdir, f"journal_{seed}.jsonl")
    j = RequestJournal(path)
    j.launch()
    rng = random.Random(seed)
    per = 4
    rids = [[f"s{seed}w{w}r{i}" for i in range(per)] for w in range(3)]
    crash_at = rng.randrange(3 * per)
    acked = {"n": 0, "crashed": False}
    mu = threading.Lock()

    class _Req:
        def __init__(self, rid):
            self.rid = rid
            self.prompt_ids = np.asarray([1, 2, 3], np.int32)
            self.max_new_tokens = 4
            self.eos_token_id = None
            self.deadline_s = None
            self.priority = 0

    def worker(widx):
        def body(sched):
            for rid in rids[widx]:
                sched.step()
                with mu:
                    if acked["crashed"]:
                        return  # post-crash work happens in replay
                    j.submitted(_Req(rid))
                sched.step()
                with mu:
                    if acked["crashed"]:
                        return
                    if acked["n"] == crash_at and not acked["crashed"]:
                        # torn-tail kill: a half-written line after the
                        # last durable ack
                        j._f.write('{"event": "done", "rid": "torn')
                        j._f.flush()
                        j.close()
                        acked["crashed"] = True
                        return
                    j.done(rid, [1, 2])
                    acked["n"] += 1
        return body

    sched = DrillScheduler(seed)
    sched.run([worker(i) for i in range(3)])
    if not acked["crashed"]:
        j.close()
    # relaunch: reopen, replay exactly the pending set
    j2 = RequestJournal(path)
    j2.launch()
    pending = j2.pending_rids()
    for rid in pending:
        j2.done(rid, [9])
    expected = sorted(j2.submitted_rids())
    report = j2.exactly_once_report(expected)
    j2.close()
    if not report["exactly_once"]:
        raise ScheduleViolation(
            f"seed {seed}: journal not exactly-once: {report}")
    return {"submitted": len(expected), "replayed": len(pending),
            "crashed": acked["crashed"], "launches": report["launches"]}


# ---------------------------------------------------------------------------
# Drill 3: checkpoint manager async save vs reader
# ---------------------------------------------------------------------------

def drill_checkpoint(seed: int, workdir: str) -> dict:
    """Async saves racing latest_complete/restore with one seeded
    snapshot corruption: the reader always lands on a validating
    snapshot and restored state round-trips bitwise."""
    from paddle_tpu.fault.checkpoint_manager import CheckpointManager
    from paddle_tpu.distributed import checkpoint as dckpt

    d = os.path.join(workdir, f"ckpt_{seed}")
    shutil.rmtree(d, ignore_errors=True)
    mgr = CheckpointManager(d, keep=3, async_save=True)
    rng = random.Random(seed)
    states = {s: {"w": np.full((4, 4), s, np.float32),
                  "b": np.arange(4, dtype=np.int64) + s}
              for s in range(1, 5)}
    corrupt_after = rng.randrange(2, 5)
    stats = {"saves": 0, "reads": 0, "skips": 0}

    def writer(sched):
        for s in sorted(states):
            sched.step()
            mgr.save(s, states[s])
            stats["saves"] += 1
            if s == corrupt_after:
                sched.step()
                mgr.wait()
                # corrupt the newest committed snapshot: truncate one
                # array file — crc validation must reject it
                step = max(mgr.all_steps())
                for fn in sorted(os.listdir(mgr._final_dir(step))):
                    if fn.endswith(".npy"):
                        p = os.path.join(mgr._final_dir(step), fn)
                        with open(p, "r+b") as f:
                            f.truncate(max(0, os.path.getsize(p) - 7))
                        break

    def reader(sched):
        for _ in range(5):
            sched.step()
            latest = mgr.latest_complete()
            stats["reads"] += 1
            if latest is None:
                continue
            ok, reason = dckpt.validate_snapshot(mgr._final_dir(latest))
            if not ok:
                raise ScheduleViolation(
                    f"seed {seed}: latest_complete returned invalid "
                    f"snapshot step_{latest}: {reason}")
            step, state, _meta = mgr.restore(latest)
            ref = states[step]
            for k in ref:
                if state[k].tobytes() != ref[k].tobytes():
                    raise ScheduleViolation(
                        f"seed {seed}: restore of step_{step} key {k!r} "
                        "is not bitwise")

    sched = DrillScheduler(seed)
    sched.run([writer, reader])
    mgr.wait()
    stats["skips"] = sum(1 for dg in mgr.diagnostics
                         if "torn/corrupt" in dg.message)
    latest = mgr.latest_complete()
    if latest is None:
        raise ScheduleViolation(f"seed {seed}: no valid snapshot survived")
    if mgr.degraded:
        raise ScheduleViolation(
            f"seed {seed}: manager degraded without a storage fault")
    mgr.close()
    shutil.rmtree(d, ignore_errors=True)
    return stats


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

DRILLS = ("prefix", "journal", "checkpoint")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seeds", type=int, default=50,
                   help="number of distinct schedule seeds per drill")
    p.add_argument("--quick", action="store_true",
                   help="tier-1 mode: 20 seeds per drill")
    p.add_argument("--drill", choices=DRILLS, action="append",
                   help="run only the named drill(s)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    a = p.parse_args(argv)
    n_seeds = 20 if a.quick else a.seeds
    drills = a.drill or list(DRILLS)

    from paddle_tpu.analysis import concurrency_check as cc
    from paddle_tpu.core.flags import set_flags
    set_flags({"lockcheck": True})
    cc.reset_runtime()

    report = {"seeds": n_seeds, "drills": {}, "violations": []}
    workdir = tempfile.mkdtemp(prefix="race_drill_")
    try:
        for name in drills:
            agg = {}
            for seed in range(n_seeds):
                try:
                    if name == "prefix":
                        st = drill_prefix(seed)
                    elif name == "journal":
                        st = drill_journal(seed, workdir)
                    else:
                        st = drill_checkpoint(seed, workdir)
                except ScheduleViolation as e:
                    report["violations"].append(f"{name}: {e}")
                    continue
                for k, v in st.items():
                    agg[k] = agg.get(k, 0) + (int(v) if not
                                              isinstance(v, bool)
                                              else int(v))
            report["drills"][name] = agg
            if not a.json:
                print(f"== {name}: {n_seeds} schedule(s), {agg}")
        # lockdep cross-check over everything the schedules witnessed
        static = cc.acquisition_graph(
            cc.collect_module_facts(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        order = cc.check_runtime_order(static)
        report["runtime_lock_edges"] = len(cc.runtime_edges())
        report["lock_order_diagnostics"] = [d.to_json() for d in order]
        if not a.json:
            print(f"== lockdep: {report['runtime_lock_edges']} witnessed "
                  f"edge(s), {len(order)} inversion(s)")
            for d in order:
                print("  " + d.format())
        if order:
            report["violations"] += [d.format() for d in order]
    finally:
        set_flags({"lockcheck": False})
        shutil.rmtree(workdir, ignore_errors=True)
    ok = not report["violations"]
    report["ok"] = ok
    if a.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"race drill: {len(drills)} drill(s) x {n_seeds} seed(s): "
              + ("OK" if ok else "VIOLATIONS:"))
        for v in report["violations"]:
            print("  " + v)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
