#!/usr/bin/env python
"""Serving fault drill: serve -> kill -> relaunch -> replay -> verify.

    python tools/serve_drill.py --quick            # tier-1-safe: tiny GPT,
                                                   # 2 kills (mid-decode +
                                                   # mid-spill), CPU
    python tools/serve_drill.py --quick --json     # report JSON on stdout
    python tools/serve_drill.py --requests 12 --decode-kill 6

Runs the serving engine as a subprocess pod under the elastic manager
with deterministic SIGKILLs delivered through the engine's fault seams
(``serve.mid_decode`` — after an iteration's compute, before any token
commit; ``serve.mid_spill`` — inside the paged host spill, before the
blocks are freed). Every incarnation replays exactly the
submitted-but-unacknowledged requests from the fsynced request journal,
then the driver asserts:

- zero lost requests and zero duplicated requests (exactly-once);
- every served output token-exact vs ``model.generate`` (greedy);
- every planned kill actually fired, one relaunch per kill.

Exits nonzero when any of those fail.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--quick", action="store_true",
                   help="tier-1-safe drill: tiny model, 2 kills")
    p.add_argument("--workdir", default=None,
                   help="drill scratch dir (default: a fresh temp dir)")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--max-new", type=int, default=None)
    p.add_argument("--seed", type=int, default=None,
                   help="trace seed (prompt contents/lengths)")
    p.add_argument("--num-blocks", type=int, default=None)
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--decode-kill", type=int, default=None,
                   help="decode iteration of the mid-decode SIGKILL")
    p.add_argument("--spill-kill", type=int, default=None,
                   help="spill ordinal of the mid-spill SIGKILL")
    p.add_argument("--prefix-cache", action="store_true",
                   help="arm FLAGS_serve_prefix_cache in the worker and "
                        "give the trace an 8-token shared prefix — the "
                        "relaunch replay must re-attach to surviving "
                        "shared pages and stay token-exact")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--out", default=None, help="also write the report here")
    args = p.parse_args(argv)

    from paddle_tpu.serving import drill

    over = {}
    for key, val in (("requests", args.requests), ("max_new", args.max_new),
                     ("trace_seed", args.seed),
                     ("num_blocks", args.num_blocks),
                     ("max_batch", args.max_batch)):
        if val is not None:
            over[key] = val
    if args.prefix_cache:
        over["prefix_cache"] = 1
        over["shared_prefix"] = 8
    events = list(drill.quick_serve_config()["events"])
    if args.decode_kill is not None:
        events[0] = ("mid_decode", args.decode_kill)
    if args.spill_kill is not None:
        events[1] = ("mid_spill", args.spill_kill)
    over["events"] = tuple(events)

    workdir = args.workdir or tempfile.mkdtemp(prefix="serve_drill_")
    report = drill.run_serve_drill(workdir, **over)
    report["workdir"] = workdir

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(drill.report_summary(report))
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
