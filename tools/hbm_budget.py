#!/usr/bin/env python
"""Static HBM-footprint accounting for training configs.

Answers, before any compile: does this (model, optimizer, offload mode,
batch) fit the chip? The categories mirror the runtime placement decided
by ``framework/offload.py``:

  params (bf16) | grads (bf16) | f32 master | moments (HBM-resident, or
  host-side with ~2 blocks in flight under FLAGS_offload_optimizer=
  moments) | activation checkpoints (remat: one block-boundary tensor per
  layer) | remat working set | logits/CE transient

``bench.py`` calls :func:`gpt_plan` before launching the full-depth
GPT-1.3B measured run, records the plan in the emitted JSON ``extra``,
and uses :func:`choose_batch` to pick the largest batch that fits. The
arithmetic is validated against the depths that are KNOWN to fit or not:
L=12 resident Adam at batch 4 fits (BENCH_r05 measured point), L=24
resident Adam does not (18.4 GB state > 15.75 GB — the reason the
flagship number was an extrapolation for two rounds), L=24 offloaded
Adam and L=24 SGD-no-moment must.

CLI:
    python tools/hbm_budget.py --layers 24 --offload moments
    python tools/hbm_budget.py --layers 24 --optimizer sgd --batch 4
exits nonzero when the config does not fit the budget.
"""

from __future__ import annotations

import argparse
import json
import sys

GB = float(2 ** 30)

# v5e: 16 GiB HBM, 15.75 GiB addressable by the program (the remainder is
# runtime-reserved); the ISSUE/BASELINE budget figure.
DEFAULT_BUDGET_GB = 15.75

# f32 moment bytes per parameter, per optimizer family (matches
# Optimizer.offloadable_state_keys()).
MOMENT_BYTES = {"adam": 8, "adamw": 8, "lamb": 8, "momentum": 4,
                "lars": 4, "sgd": 0}


def gpt_param_counts(layers: int, hidden: int, seq: int, vocab: int):
    """(total, per_layer, misc) param counts of the repo's GPT decoder
    (qkv/out/mlp-4x + 2 LN per block; untied LM head reuses wte).
    Validated exactly against the built model: 1,315,819,520 at
    L=24 h=2048 seq=2048 vocab=50304."""
    per_layer = 12 * hidden * hidden + 13 * hidden
    misc = vocab * hidden + seq * hidden + 2 * hidden  # wte + wpe + ln_f
    return misc + layers * per_layer, per_layer, misc


def gpt_plan(layers: int = 24, hidden: int = 2048, heads: int = 16,
             seq: int = 2048, batch: int = 4, vocab: int = 50304,
             optimizer: str = "adamw", offload: str = "off",
             remat: bool = True, multi_precision: bool = True,
             param_bytes: int = 2, budget_gb: float = DEFAULT_BUDGET_GB):
    """Byte plan dict for one GPT training config. ``fits`` compares the
    device-resident total against ``budget_gb``."""
    n, per_layer, misc = gpt_param_counts(layers, hidden, seq, vocab)
    moment_b = MOMENT_BYTES.get(optimizer.lower())
    if moment_b is None:
        raise ValueError(f"unknown optimizer {optimizer!r}; "
                         f"known: {sorted(MOMENT_BYTES)}")
    rows = {
        "params": n * param_bytes,
        "grads": n * param_bytes,
        "master": n * 4 if (multi_precision and param_bytes < 4) else 0,
    }
    host_rows = {}
    moments = n * moment_b
    if offload == "moments" and moments:
        host_rows["host_moments"] = moments
        # in flight: current + prefetched block; worst pair is the misc
        # (embedding) block next to a trunk block
        rows["moments_in_flight"] = (misc + per_layer) * moment_b
    else:
        rows["moments"] = moments
    tok = batch * seq
    if remat:
        # saved: one bf16 block-boundary activation per layer; working
        # set: one block's recomputed fwd+bwd intermediates (qkv 3h +
        # attn out h + mlp 8h + norms ~2h ≈ 14h widths, bf16)
        rows["act_checkpoints"] = layers * tok * hidden * 2
        rows["remat_working"] = 14 * tok * hidden * 2
    else:
        rows["activations"] = layers * 14 * tok * hidden * 2
    # LM head transient: bf16 logits + f32 softmax/CE + f32 dlogits
    rows["logits_ce"] = tok * vocab * (2 + 4 + 4)
    device_total = sum(rows.values())
    return {
        "config": {"layers": layers, "hidden": hidden, "heads": heads,
                   "seq": seq, "batch": batch, "vocab": vocab,
                   "optimizer": optimizer, "offload": offload,
                   "remat": remat, "n_params": n},
        "rows_gb": {k: round(v / GB, 3) for k, v in rows.items()},
        "host_gb": round(sum(host_rows.values()) / GB, 3),
        "device_gb": round(device_total / GB, 3),
        "budget_gb": budget_gb,
        "headroom_gb": round(budget_gb - device_total / GB, 3),
        "fits": device_total / GB <= budget_gb,
    }


def choose_batch(candidates=(4, 2, 1), **kwargs):
    """Largest candidate batch whose plan fits (None if none do), plus
    that plan — bench's pre-launch gate."""
    for b in candidates:
        plan = gpt_plan(batch=b, **kwargs)
        if plan["fits"]:
            return b, plan
    return None, gpt_plan(batch=candidates[-1], **kwargs)


def tier_plan(offload: str = "off", remat: bool = True,
              optimizer: str = "adamw", **kwargs):
    """The capacity plan a composed tier set is held to by the flag-matrix
    gate (``tools/lint_graph.py --matrix`` / ``analysis/plan_check`` rule
    D004): full-depth GPT-1.3B when the moments are offloaded, the L=12
    half-depth otherwise — resident Adam state alone exceeds HBM at L=24,
    which is exactly the wall the offload tier exists to remove. Returns
    the largest-fitting-batch plan (``fits`` False when even batch 1
    does not fit under the composition)."""
    layers = kwargs.pop("layers", 24 if offload == "moments" else 12)
    _, plan = choose_batch(layers=layers, optimizer=optimizer,
                           offload=offload, remat=remat, **kwargs)
    return plan


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--layers", type=int, default=24)
    p.add_argument("--hidden", type=int, default=2048)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--vocab", type=int, default=50304)
    p.add_argument("--optimizer", default="adamw",
                   choices=sorted(MOMENT_BYTES))
    p.add_argument("--offload", default="off", choices=["off", "moments"])
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--budget-gb", type=float, default=DEFAULT_BUDGET_GB)
    a = p.parse_args(argv)
    plan = gpt_plan(layers=a.layers, hidden=a.hidden, heads=a.heads,
                    seq=a.seq, batch=a.batch, vocab=a.vocab,
                    optimizer=a.optimizer, offload=a.offload,
                    remat=not a.no_remat, budget_gb=a.budget_gb)
    print(json.dumps(plan, indent=2))
    return 0 if plan["fits"] else 1


if __name__ == "__main__":
    sys.exit(main())
