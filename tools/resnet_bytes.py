"""Where do the ResNet step's HBM bytes go? Aggregates hlo_stats rows
(bytes ~= measured bw x self-time) by op-name bucket.

Usage: python tools/resnet_bytes.py [fused|pallas|plain]

``pallas`` additionally routes the fused units through the Pallas conv
kernel family (FLAGS_pallas_conv — ops/_pallas/conv.py). The top-3
byte-dominant conv shape classes this profile identified (r5, batch 256)
are recorded as ``RESNET50_TOP3_SHAPES`` in that module; the per-shape
kernel A/B against them runs via ``BENCH_PALLAS_CONV=1 python bench.py``.
"""
import functools
import glob
import json
import os
import re
import shutil
import sys
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as paddle
from paddle_tpu.core import flags as _flags
from paddle_tpu.framework.functional import (functional_call, get_buffers,
                                             get_params)
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import fused_conv_bn  # noqa: F401  (define flag)
from paddle_tpu.optimizer import Momentum
from paddle_tpu.vision.models import resnet50

mode = sys.argv[1] if len(sys.argv) > 1 else "plain"
_flags.set_flags({"fused_conv_bn": 1 if mode in ("fused", "pallas") else 0})
if mode == "pallas":
    from paddle_tpu.ops._pallas import conv as _pconv  # noqa: F401
    _flags.set_flags({"pallas_conv": 1})

batch, img, steps = 256, 224, 6
paddle.seed(0)
model = resnet50(data_format="NHWC")
model.train()
model.astype(paddle.bfloat16)
opt = Momentum(learning_rate=0.1, momentum=0.9, multi_precision=True)
params = get_params(model)
buffers = get_buffers(model)
opt_state = opt.init(params)


def loss_of(p, buf, x, y):
    out, new_buf = functional_call(model, p, x, buffers=buf, mutable=True,
                                   training=True)
    return F.cross_entropy(out.astype(jnp.float32), y,
                           reduction="mean"), new_buf


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, x, y):
    p, buf, st = state
    (loss, new_buf), grads = jax.value_and_grad(
        loss_of, has_aux=True)(p, buf, x, y)
    new_p, new_st = opt.apply_gradients(p, grads, st, 0.1)
    return loss, (new_p, new_buf, new_st)


rng = np.random.default_rng(0)
x = jnp.asarray(rng.standard_normal((batch, img, img, 3)), jnp.bfloat16)
y = jnp.asarray(rng.integers(0, 1000, (batch,)), jnp.int32)
state = (params, buffers, opt_state)
loss, state = step(state, x, y)
loss, state = step(state, x, y)
float(loss)

tracedir = tempfile.mkdtemp(prefix="rn_bytes_")
with jax.profiler.trace(tracedir):
    for _ in range(steps):
        loss, state = step(state, x, y)
    float(loss)

from xprof.convert import raw_to_tool_data as rtd  # noqa: E402
xplane = glob.glob(os.path.join(
    sorted(glob.glob(os.path.join(tracedir, "plugins/profile/*")))[-1],
    "*.xplane.pb"))
data, _ = rtd.xspace_to_tool_data(xplane, "hlo_stats", {})
d = json.loads(data.decode() if isinstance(data, bytes) else data)
shutil.rmtree(tracedir, ignore_errors=True)
cols = [c["id"] for c in d["cols"]]
print("columns:", cols)
rows = [[c.get("v") for c in r["c"]] for r in d["rows"]]
i = {c: cols.index(c) for c in cols}

def g(r, name, default=0.0):
    idx = i.get(name)
    return r[idx] if idx is not None and r[idx] is not None else default

# shape-class bucket: the widest output tensor shape mentioned in the expr
SHAPE_RE = re.compile(r"(bf16|f32)\[([0-9,]+)\]")

def bucket(expr, cat):
    shapes = SHAPE_RE.findall(expr or "")
    best, bestn = "", 0
    for dt, s in shapes:
        dims = [int(v) for v in s.split(",") if v]
        n = int(np.prod(dims)) if dims else 0
        if n > bestn:
            bestn, best = n, f"{dt}[{s}]"
    return f"{cat:22s} {best}"

tot_ms = tot_gb = 0.0
agg = {}
for r in rows:
    ms = g(r, "total_self_time") / 1e3
    bw = g(r, "measured_memory_bw")      # GiB/s? assume GB/s
    gb = bw * (ms / 1e3)
    tot_ms += ms
    tot_gb += gb
    key = bucket(str(g(r, "hlo_op_expression", "")), str(g(r, "category", "")))
    a = agg.setdefault(key, [0.0, 0.0, 0])
    a[0] += ms; a[1] += gb; a[2] += int(g(r, "occurrences", 0))
print(f"mode={mode} total {tot_ms/steps:.2f} ms/step, "
      f"~{tot_gb/steps:.1f} GB/step (bw-derived)")
for key, (ms, gb, occ) in sorted(agg.items(), key=lambda kv: -kv[1][1])[:35]:
    print(f"  {gb/steps:7.2f} GB  {ms/steps:8.3f} ms  x{occ/steps:5.1f}  {key}")
