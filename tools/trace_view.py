#!/usr/bin/env python
"""Aggregate a telemetry JSONL dump into the per-phase table + anomalies.

Input is the JSONL written by ``StepTimeline.export_jsonl`` (one record
per step: ``{"kind": "step", "step": N, "phases": {...}, "total_ms": ..,
"hbm_peak_gb": ..}``), optionally interleaved with ``trace.export_jsonl``
span records (``{"kind": "span", "name": .., "dur_us": ..}``) — bench runs
write both into one file.

    python tools/trace_view.py BENCH_timeline.jsonl
    python tools/trace_view.py run.jsonl --json          # machine output
    python tools/trace_view.py run.jsonl --factor 2.5    # anomaly knob

Anomaly rule: a step whose ``total_ms`` exceeds ``factor`` (default 3x)
times the rolling median of the preceding ``window`` steps is flagged —
the post-hoc version of bench.py's roofline guard, usable on any recorded
run without knowing the model.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
from typing import Any, Dict, List, Tuple

# Steps of history required before the rolling median is trusted; earlier
# steps (incl. the compile-heavy first ones) are never flagged.
MIN_HISTORY = 5


def load_jsonl(path: str) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """(step_records, span_records) from one JSONL file; unknown or broken
    lines are skipped (a truncated tail must not kill the report)."""
    steps, spans = [], []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            kind = rec.get("kind")
            if kind == "step" or ("phases" in rec and "step" in rec):
                steps.append(rec)
            elif kind == "span":
                spans.append(rec)
    steps.sort(key=lambda r: r.get("step", 0))
    return steps, spans


def phase_table(steps: List[Dict[str, Any]],
                spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-phase aggregate rows sorted by total time descending."""
    agg: Dict[str, Dict[str, float]] = {}

    def add(name: str, ms: float):
        row = agg.setdefault(name, {"calls": 0, "total_ms": 0.0,
                                    "max_ms": 0.0})
        row["calls"] += 1
        row["total_ms"] += ms
        row["max_ms"] = max(row["max_ms"], ms)

    for s in steps:
        for name, ms in (s.get("phases") or {}).items():
            add(name, float(ms))
    for sp in spans:
        # span names are "step/<phase>" (step_monitor) or free-form
        name = sp.get("name", "")
        if name.startswith("step/"):
            continue  # already counted via the step record's phases
        if name:
            add(f"span:{name}", float(sp.get("dur_us", 0.0)) / 1e3)

    total = sum(r["total_ms"] for r in agg.values()) or 1.0
    rows = []
    for name in sorted(agg, key=lambda n: -agg[n]["total_ms"]):
        r = agg[name]
        rows.append({
            "phase": name,
            "calls": r["calls"],
            "total_ms": round(r["total_ms"], 3),
            "avg_ms": round(r["total_ms"] / max(r["calls"], 1), 3),
            "max_ms": round(r["max_ms"], 3),
            "share_pct": round(100.0 * r["total_ms"] / total, 1),
        })
    return rows


def find_anomalies(steps: List[Dict[str, Any]], factor: float = 3.0,
                   window: int = 32) -> List[Dict[str, Any]]:
    """Steps slower than ``factor`` x the rolling median of the preceding
    ``window`` steps' total_ms."""
    out = []
    history: List[float] = []
    for s in steps:
        t = s.get("total_ms")
        if t is None:
            continue
        if len(history) >= MIN_HISTORY:
            med = statistics.median(history[-window:])
            if med > 0 and t > factor * med:
                out.append({"step": s.get("step"),
                            "total_ms": round(float(t), 3),
                            "rolling_median_ms": round(med, 3),
                            "slowdown_x": round(float(t) / med, 2),
                            "phases": s.get("phases", {})})
        history.append(float(t))
    return out


def comm_summary(steps: List[Dict[str, Any]],
                 spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate the communication-overlap tier's signal: the ``comm``
    step phase (dispatch-level bucketed reductions) plus ``comm/*`` spans
    (decomposed collective-matmul call sites — their attrs carry the
    static hop plan: hop count, bytes per hop, axis size)."""
    phase_ms = 0.0
    phase_calls = 0
    for s in steps:
        ms = (s.get("phases") or {}).get("comm")
        if ms is not None:
            phase_ms += float(ms)
            phase_calls += 1
    ops: Dict[str, Dict[str, float]] = {}
    for sp in spans:
        name = sp.get("name", "")
        if not name.startswith("comm/"):
            continue
        attrs = sp.get("attrs") or {}
        row = ops.setdefault(name[len("comm/"):],
                             {"calls": 0, "total_ms": 0.0, "hops": 0,
                              "bytes_moved": 0})
        row["calls"] += 1
        row["total_ms"] += float(sp.get("dur_us", 0.0)) / 1e3
        hops = int(attrs.get("hops", 0))
        row["hops"] += hops
        row["bytes_moved"] += hops * int(attrs.get("bytes_per_hop", 0))
    for row in ops.values():
        row["total_ms"] = round(row["total_ms"], 3)
    return {
        "phase_total_ms": round(phase_ms, 3),
        "phase_steps": phase_calls,
        "decomposed_ops": ops,
    }


def summarize(steps: List[Dict[str, Any]], spans: List[Dict[str, Any]],
              factor: float = 3.0, window: int = 32) -> Dict[str, Any]:
    totals = [float(s["total_ms"]) for s in steps if "total_ms" in s]
    hbm = [s.get("hbm_peak_gb") for s in steps
           if s.get("hbm_peak_gb") is not None]
    return {
        "steps": len(steps),
        "spans": len(spans),
        "avg_step_ms": round(sum(totals) / len(totals), 3) if totals else None,
        "median_step_ms": round(statistics.median(totals), 3)
        if totals else None,
        "max_step_ms": round(max(totals), 3) if totals else None,
        "hbm_peak_gb": max(hbm) if hbm else None,
        "phases": phase_table(steps, spans),
        "comm": comm_summary(steps, spans),
        "anomalies": find_anomalies(steps, factor=factor, window=window),
    }


def render_text(summary: Dict[str, Any]) -> str:
    bar = "-" * 72
    lines = [bar, "Telemetry timeline", bar]
    lines.append(
        f"steps: {summary['steps']}   avg: {summary['avg_step_ms']} ms   "
        f"median: {summary['median_step_ms']} ms   "
        f"max: {summary['max_step_ms']} ms" +
        (f"   hbm peak: {summary['hbm_peak_gb']} GB"
         if summary["hbm_peak_gb"] is not None else ""))
    lines.append(bar)
    lines.append(f"{'phase':<24}{'calls':>7}{'total ms':>12}{'avg ms':>10}"
                 f"{'max ms':>10}{'share':>8}")
    for r in summary["phases"]:
        lines.append(f"{r['phase'][:23]:<24}{r['calls']:>7}"
                     f"{r['total_ms']:>12.3f}{r['avg_ms']:>10.3f}"
                     f"{r['max_ms']:>10.3f}{r['share_pct']:>7.1f}%")
    comm = summary.get("comm") or {}
    if comm.get("phase_total_ms") or comm.get("decomposed_ops"):
        lines.append(bar)
        lines.append(
            f"comm overlap: {comm['phase_total_ms']} ms dispatch-level "
            f"across {comm['phase_steps']} step(s)")
        for op, row in sorted(comm["decomposed_ops"].items()):
            lines.append(
                f"  {op}: {row['calls']} call(s), {row['hops']} hops, "
                f"{row['bytes_moved'] / 2**20:.2f} MiB moved, "
                f"{row['total_ms']} ms")
    anomalies = summary["anomalies"]
    lines.append(bar)
    if anomalies:
        lines.append(f"{len(anomalies)} anomalous step(s) "
                     "(> factor x rolling median):")
        for a in anomalies:
            lines.append(
                f"  step {a['step']}: {a['total_ms']} ms "
                f"({a['slowdown_x']}x the rolling median "
                f"{a['rolling_median_ms']} ms)")
    else:
        lines.append("no step-time anomalies")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", help="telemetry JSONL file")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary")
    p.add_argument("--factor", type=float, default=3.0,
                   help="anomaly threshold vs rolling median (default 3.0)")
    p.add_argument("--window", type=int, default=32,
                   help="rolling-median window in steps (default 32)")
    p.add_argument("--fail-on-anomaly", action="store_true",
                   help="exit nonzero when any step is anomalous (CI gate)")
    a = p.parse_args(argv)
    steps, spans = load_jsonl(a.path)
    summary = summarize(steps, spans, factor=a.factor, window=a.window)
    if a.json:
        print(json.dumps(summary, indent=2))
    else:
        print(render_text(summary))
    if a.fail_on_anomaly and summary["anomalies"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
