"""Text datasets (``paddle.text.datasets`` parity).

Reference: ``python/paddle/text/datasets/`` — Imdb, Imikolov, UCIHousing,
Movielens, Conll05, WMT16, each a map-style Dataset downloading a public
corpus. This environment has zero network egress, so every dataset generates
a deterministic synthetic corpus with the *same field structure, dtypes, and
value ranges* as the real one (the same policy as
``vision/datasets``' synthetic MNIST): models and input pipelines exercise
identical shapes; swap in real data by subclassing and overriding
``_generate``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens", "Conll05",
           "Conll05st", "WMT14",
           "WMT16"]


def _rng(mode: str, salt: int) -> np.random.Generator:
    return np.random.default_rng(salt + (0 if mode == "train" else 1))


class Imdb(Dataset):
    """Binary sentiment corpus: (word-id sequence, label in {0, 1})
    (ref ``text/datasets/imdb.py``)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, synthetic_size: Optional[int] = None,
                 seq_len: int = 64, vocab_size: int = 5147):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train/test, got {mode!r}")
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}
        n = synthetic_size or (2000 if mode == "train" else 500)
        rng = _rng(mode, 101)
        self.labels = rng.integers(0, 2, size=(n,)).astype(np.int64)
        # Sentiment signal: positive docs draw from the high half of the
        # vocab more often, so the synthetic task is learnable.
        self.docs = []
        for y in self.labels:
            bias = 0.75 if y else 0.25
            split = vocab_size // 2
            low = rng.integers(0, split, size=(seq_len,))
            high = rng.integers(split, vocab_size, size=(seq_len,))
            pick = rng.random(seq_len) < bias
            self.docs.append(np.where(pick, high, low).astype(np.int64))

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM tuples (ref ``text/datasets/imikolov.py``):
    each item is an n-gram of word ids, the last being the target."""

    def __init__(self, data_file: Optional[str] = None, data_type: str = "NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50, synthetic_size: Optional[int] = None,
                 vocab_size: int = 2074):
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError(f"data_type must be NGRAM or SEQ, got {data_type}")
        self.data_type = data_type
        self.window_size = window_size
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}
        n = synthetic_size or (1500 if mode == "train" else 300)
        rng = _rng(mode, 202)
        # Markov-ish stream: next word correlated with previous (learnable).
        stream = np.zeros(n + window_size, dtype=np.int64)
        stream[0] = rng.integers(0, vocab_size)
        for i in range(1, len(stream)):
            stream[i] = (stream[i - 1] * 31 + rng.integers(0, 7)) % vocab_size
        self._grams = [stream[i:i + window_size].copy() for i in range(n)]

    def __getitem__(self, idx):
        g = self._grams[idx]
        if self.data_type == "NGRAM":
            return tuple(g)
        return g[:-1], g[1:]

    def __len__(self):
        return len(self._grams)


class UCIHousing(Dataset):
    """Boston-housing regression: 13 fp32 features -> price
    (ref ``text/datasets/uci_housing.py``)."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 synthetic_size: Optional[int] = None):
        n = synthetic_size or (404 if mode == "train" else 102)
        rng = _rng(mode, 303)
        self.features = rng.standard_normal((n, self.FEATURE_DIM)) \
            .astype(np.float32)
        w = np.linspace(-1.0, 1.0, self.FEATURE_DIM).astype(np.float32)
        noise = 0.1 * rng.standard_normal(n).astype(np.float32)
        self.prices = (self.features @ w + noise).reshape(n, 1)

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.features)


class Movielens(Dataset):
    """Rating tuples (user_id, gender, age, job, movie_id, category, title,
    rating) (ref ``text/datasets/movielens.py``)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0,
                 synthetic_size: Optional[int] = None,
                 n_users: int = 6040, n_movies: int = 3952):
        n = synthetic_size or (4000 if mode == "train" else 400)
        rng = _rng(mode, 404 + rand_seed)
        self.max_user_id = n_users
        self.max_movie_id = n_movies
        users = rng.integers(1, n_users + 1, n)
        movies = rng.integers(1, n_movies + 1, n)
        # Rating correlated with (user+movie) parity for learnability.
        base = ((users + movies) % 5 + 1)
        jitter = rng.integers(-1, 2, n)
        self._rows = [(
            np.int64(u), np.int64(rng.integers(0, 2)),
            np.int64(rng.integers(1, 8)), np.int64(rng.integers(0, 21)),
            np.int64(m), np.int64(rng.integers(0, 18)),
            rng.integers(0, 5000, size=(8,)).astype(np.int64),
            np.float32(np.clip(b + j, 1, 5)),
        ) for u, m, b, j in zip(users, movies, base, jitter)]

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class Conll05(Dataset):
    """SRL tuples: (word_ids, ctx_n2/n1/0/p1/p2, predicate, mark, labels)
    (ref ``text/datasets/conll05.py``)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 synthetic_size: Optional[int] = None, seq_len: int = 30,
                 word_vocab: int = 44068, label_vocab: int = 59,
                 predicate_vocab: int = 3162):
        n = synthetic_size or (1000 if mode == "train" else 200)
        self.word_dict = {f"w{i}": i for i in range(word_vocab)}
        self.label_dict = {f"l{i}": i for i in range(label_vocab)}
        self.predicate_dict = {f"p{i}": i for i in range(predicate_vocab)}
        rng = _rng(mode, 505)
        self._rows = []
        for _ in range(n):
            words = rng.integers(0, word_vocab, seq_len).astype(np.int64)
            ctx = [np.roll(words, s) for s in (2, 1, 0, -1, -2)]
            pred_pos = rng.integers(0, seq_len)
            predicate = np.full(seq_len, rng.integers(0, predicate_vocab),
                                dtype=np.int64)
            mark = np.zeros(seq_len, dtype=np.int64)
            mark[pred_pos] = 1
            labels = rng.integers(0, label_vocab, seq_len).astype(np.int64)
            self._rows.append((words, *ctx, predicate, mark, labels))

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class WMT16(Dataset):
    """Translation pairs (src ids, trg ids, trg_next ids) with <s>/<e>/<unk>
    conventions (ref ``text/datasets/wmt16.py``)."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = 10000, trg_dict_size: int = 10000,
                 lang: str = "en", synthetic_size: Optional[int] = None,
                 seq_len: int = 20):
        if mode not in ("train", "test", "val"):
            raise ValueError(f"mode must be train/test/val, got {mode!r}")
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        n = synthetic_size or {"train": 1600, "val": 320, "test": 320}[mode]
        # Distinct stream per split (val must not alias test).
        rng = np.random.default_rng(
            606 + {"train": 0, "val": 1, "test": 2}[mode])
        self._rows = []
        for _ in range(n):
            L = int(rng.integers(seq_len // 2, seq_len))
            src = rng.integers(3, src_dict_size, L).astype(np.int64)
            # Deterministic "translation": affine remap into the target vocab.
            trg_core = ((src * 7 + 13) % (trg_dict_size - 3) + 3)
            trg = np.concatenate([[self.BOS], trg_core]).astype(np.int64)
            trg_next = np.concatenate([trg_core, [self.EOS]]).astype(np.int64)
            self._rows.append((src, trg, trg_next))

    def get_dict(self, lang: str = "en", reverse: bool = False):
        size = self.src_dict_size if lang == "en" else self.trg_dict_size
        d = {"<s>": self.BOS, "<e>": self.EOS, "<unk>": self.UNK}
        d.update({f"tok{i}": i for i in range(3, size)})
        if reverse:
            return {v: k for k, v in d.items()}
        return d

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class WMT14(WMT16):
    """ref text/datasets/wmt14.py — same synthetic translation-pair
    surface as WMT16 (different source corpus upstream)."""


# reference class name (paddle.text.Conll05st)
Conll05st = Conll05
