"""Sequence ops (ref: paddle.text.viterbi_decode / fluid sequence ops).

viterbi_decode and gather_tree are lax.scan dynamic programs (TPU-friendly:
static shapes, no host loops); edit_distance is a host-side numpy DP (its
output is a scalar per pair and the reference computes it on CPU too).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["viterbi_decode", "edit_distance", "gather_tree", "shard_index"]


def viterbi_decode(potentials, transition, lengths=None,
                   include_bos_eos_tag: bool = False):
    """CRF Viterbi decoding (ref paddle.text.viterbi_decode /
    phi viterbi_decode kernel).

    potentials: [B, T, N] unary emission scores; transition: [N, N]
    (transition[i, j] = score of i -> j); lengths: [B] valid lengths.
    Returns (scores [B], paths [B, T]).
    """
    if include_bos_eos_tag:
        raise NotImplementedError(
            "include_bos_eos_tag=True (implicit BOS/EOS transition rows) "
            "is not implemented; append explicit BOS/EOS tags instead")
    b, t, n = potentials.shape
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)

    def step(carry, inp):
        alpha, t_idx = carry
        emit = inp  # [B, N]
        # candidate[i, j] = alpha[i] + transition[i, j]
        cand = alpha[:, :, None] + transition[None]       # [B, N, N]
        best_prev = jnp.argmax(cand, axis=1)              # [B, N]
        new_alpha = jnp.max(cand, axis=1) + emit
        # positions past a sequence's length keep their alpha frozen
        active = (t_idx < lengths)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(n)[None, :])
        return (new_alpha, t_idx + 1), best_prev

    alpha0 = potentials[:, 0]
    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, jnp.asarray(1, jnp.int32)),
        jnp.swapaxes(potentials[:, 1:], 0, 1))
    scores = jnp.max(alpha, axis=-1)
    last = jnp.argmax(alpha, axis=-1)                     # [B]

    def backward(carry, ptrs):
        tok = carry
        prev = jnp.take_along_axis(ptrs, tok[:, None], axis=1)[:, 0]
        return prev, tok

    first, path_rev = jax.lax.scan(backward, last, backptrs, reverse=True)
    paths = jnp.concatenate([first[None], path_rev], axis=0)  # [T, B]
    return scores, jnp.swapaxes(paths, 0, 1)


def edit_distance(hyps, refs, normalized: bool = True):
    """Levenshtein distance per (hyp, ref) pair (ref fluid edit_distance
    op). Accepts lists of int sequences; returns ([B, 1] distances,
    [B] sequence count). Host-side numpy DP."""
    if len(hyps) != len(refs):
        raise ValueError(
            f"edit_distance needs paired sequences; got {len(hyps)} "
            f"hypotheses vs {len(refs)} references")
    out = np.zeros((len(hyps), 1), np.float32)
    for i, (h, r) in enumerate(zip(hyps, refs)):
        h = list(np.asarray(h).reshape(-1))
        r = list(np.asarray(r).reshape(-1))
        m, n = len(h), len(r)
        dp = np.arange(n + 1, dtype=np.int64)
        for x in range(1, m + 1):
            prev_diag = dp[0]
            dp[0] = x
            for y in range(1, n + 1):
                cur = dp[y]
                dp[y] = min(dp[y] + 1, dp[y - 1] + 1,
                            prev_diag + (h[x - 1] != r[y - 1]))
                prev_diag = cur
        d = float(dp[n])
        out[i, 0] = d / max(n, 1) if normalized else d
    return jnp.asarray(out), jnp.asarray(len(hyps))


def gather_tree(ids, parents):
    """Beam-search backtrace (ref phi gather_tree kernel): follow parent
    pointers from the last step so every step holds the token of its final
    beam. ids/parents: [T, B, W]. Returns [T, B, W]."""
    t = ids.shape[0]

    def step(beams, inp):
        step_ids, step_parents = inp
        tokens = jnp.take_along_axis(step_ids, beams, axis=-1)
        parents = jnp.take_along_axis(step_parents, beams, axis=-1)
        return parents, tokens

    init = jnp.broadcast_to(jnp.arange(ids.shape[2])[None, :],
                            ids.shape[1:])
    _, out = jax.lax.scan(step, init, (ids, parents), reverse=True)
    return out


def shard_index(input, index_num: int, nshards: int, shard_id: int,
                ignore_value: int = -1):
    """Recalculate label ids for a sharded embedding/classifier
    (ref phi shard_index kernel): ids owned by `shard_id` map to their
    local offset, others to `ignore_value`."""
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (input >= lo) & (input < hi)
    return jnp.where(in_shard, input - lo, ignore_value)
