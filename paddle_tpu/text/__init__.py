from . import models  # noqa: F401
from .ops import (viterbi_decode, edit_distance,  # noqa: F401
                  gather_tree, shard_index)
from . import datasets  # noqa: F401
from .datasets import (Imdb, Imikolov, UCIHousing, Movielens,  # noqa: F401
                       Conll05, Conll05st, WMT14, WMT16)


class ViterbiDecoder:
    """ref text/viterbi_decode.py ViterbiDecoder layer form."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              include_bos_eos_tag=self.include_bos_eos_tag)

