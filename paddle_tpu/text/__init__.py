from . import models  # noqa: F401
from .ops import (viterbi_decode, edit_distance,  # noqa: F401
                  gather_tree, shard_index)
from . import datasets  # noqa: F401
