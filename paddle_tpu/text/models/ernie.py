"""ERNIE model family (BASELINE config 5: ERNIE-3.0 pipeline parallel pp=4).

ERNIE is a BERT-shaped bidirectional encoder with an extra *task-type*
embedding table (ERNIE 2.0/3.0 continual multi-task pretraining) and, for
pretraining, a tied-embedding MLM head plus a sentence-order head. The
reference ships ERNIE through PaddleNLP on top of the fleet stack; here the
model is built from the same Layer/TransformerEncoder primitives as our
BERT and exposes ``ernie_pipeline_descs`` — the LayerDesc list that drops
into ``PipelineLayer`` for the pp=4 workload (ref
fleet/meta_parallel/parallel_layers/pp_layers.py partitioning).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import ParamAttr

__all__ = ["ErnieConfig", "Ernie", "ErnieForPretraining", "ernie_base",
           "ernie_tiny", "ernie_pipeline_descs"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02


def ernie_base(**overrides) -> ErnieConfig:
    """ernie-3.0-base-zh dimensions."""
    return ErnieConfig(**overrides)


def ernie_tiny(**overrides) -> ErnieConfig:
    return ErnieConfig(**{**dict(vocab_size=1024, hidden_size=128,
                                 num_layers=2, num_heads=4,
                                 intermediate_size=512,
                                 max_position_embeddings=128), **overrides})


def _attr(cfg: ErnieConfig) -> ParamAttr:
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=_attr(cfg))
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size,
            weight_attr=_attr(cfg))
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=_attr(cfg))
        if cfg.use_task_id:
            self.task_type_embeddings = nn.Embedding(
                cfg.task_type_vocab_size, cfg.hidden_size,
                weight_attr=_attr(cfg))
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_epsilon)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None):
        s = input_ids.shape[1]
        pos = jnp.arange(s)[None, :]
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + self.token_type_embeddings(token_type_ids)
        if self.cfg.use_task_id:
            if task_type_ids is None:
                task_type_ids = jnp.zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


def _encoder_layer(cfg: ErnieConfig) -> nn.TransformerEncoderLayer:
    return nn.TransformerEncoderLayer(
        cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
        dropout=cfg.hidden_dropout, activation="gelu",
        attn_dropout=cfg.attention_dropout, weight_attr=_attr(cfg))


class Ernie(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        self.encoder = nn.TransformerEncoder(lambda: _encoder_layer(cfg),
                                             cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=_attr(cfg))

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, task_type_ids)
        mask = None
        if attention_mask is not None:
            mask = (1.0 - attention_mask[:, None, None, :].astype(x.dtype)) \
                * -1e9
        x = self.encoder(x, src_mask=mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    """Tied-embedding MLM + sentence-order prediction heads."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = Ernie(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                       weight_attr=_attr(cfg))
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_epsilon)
        self.mlm_bias = self.create_parameter((cfg.vocab_size,), is_bias=True)
        self.sop_head = nn.Linear(cfg.hidden_size, 2, weight_attr=_attr(cfg))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, sop_labels=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, None,
                                 attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        logits = jnp.matmul(
            h, self.ernie.embeddings.word_embeddings.weight.T) + self.mlm_bias
        sop_logits = self.sop_head(pooled)
        if masked_lm_labels is None:
            return logits, sop_logits
        loss = F.cross_entropy(logits, masked_lm_labels, ignore_index=-100,
                               reduction="mean")
        if sop_labels is not None:
            loss = loss + F.cross_entropy(sop_logits, sop_labels.reshape(-1),
                                          reduction="mean")
        return loss


class _ErniePipeEmbed(nn.Layer):
    """Stage-0 head for the pipeline: ids -> embedded activations."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.embeddings = ErnieEmbeddings(cfg)

    def forward(self, input_ids):
        return self.embeddings(input_ids)


class _ErniePipeBlock(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.block = _encoder_layer(cfg)

    def forward(self, x):
        return self.block(x)


class _ErniePipeHead(nn.Layer):
    """Final norm + untied MLM projection (pipeline stages cannot tie to the
    stage-0 embedding without a shared-param group; ref SharedLayerDesc)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                   weight_attr=_attr(cfg))
        self.norm = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.proj = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                              weight_attr=_attr(cfg))

    def forward(self, x):
        return self.proj(self.norm(F.gelu(self.transform(x))))


def ernie_pipeline_descs(cfg: ErnieConfig):
    """LayerDesc list for PipelineLayer (BASELINE config 5: pp=4).
    Embedding head + num_layers homogeneous encoder blocks + MLM tail; the
    pipeline analyzer keeps head/tail outside the pipelined trunk."""
    from ...distributed.fleet.meta_parallel.pp_layers import LayerDesc
    descs = [LayerDesc(_ErniePipeEmbed, cfg)]
    descs += [LayerDesc(_ErniePipeBlock, cfg) for _ in range(cfg.num_layers)]
    descs.append(LayerDesc(_ErniePipeHead, cfg))
    return descs
