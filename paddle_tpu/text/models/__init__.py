from .gpt import GPT, GPTConfig, GPTForCausalLM  # noqa: F401
from .bert import Bert, BertConfig, BertForPretraining  # noqa: F401
from .ernie import (Ernie, ErnieConfig, ErnieForPretraining,  # noqa: F401
                    ernie_base, ernie_tiny, ernie_pipeline_descs)
