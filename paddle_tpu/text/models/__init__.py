from .gpt import GPT, GPTConfig, GPTForCausalLM  # noqa: F401
from .bert import Bert, BertConfig, BertForPretraining  # noqa: F401
