"""GPT model family — the flagship (BASELINE config 4: GPT-3 1.3B hybrid
parallel).

A from-scratch decoder-only transformer built on the TP layer library: QKV
and MLP-up are column-parallel, attention-out and MLP-down are row-parallel
(Megatron sharding over the 'mp' mesh axis), attention runs through the
Pallas flash-attention op, and the lm head is the (optionally tied)
vocab-parallel projection with parallel cross-entropy. Compare the
reference's fleet GPT cases (test/collective/fleet hybrid_parallel_mp_model /
pp_model) which assemble the same structure from mp_layers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import nn
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import ParamAttr
from ...distributed.fleet.layers.mpu.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, _constrain, MP_AXIS)
from ...ops import flash_attention

__all__ = ["GPTConfig", "GPT", "GPTForCausalLM", "gpt3_1p3b", "gpt_tiny"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    # Grouped-query attention: fewer KV heads shared by query-head groups
    # (None = MHA). The Pallas flash kernel reads shared KV tiles through
    # its BlockSpec index map, so GQA adds no repeat materialization.
    num_kv_heads: Optional[int] = None
    max_position_embeddings: int = 2048
    intermediate_size: Optional[int] = None  # default 4*hidden
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True
    sequence_parallel: bool = False
    recompute: bool = False
    # jax.checkpoint_policies name used when recompute is on
    recompute_policy: str = "dots_and_flash_saveable"
    # Long-context CP over the 'sep' mesh axis: None | 'ring' | 'ulysses'.
    context_parallel: Optional[str] = None

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def kv_heads(self) -> int:
        # explicit None check: num_kv_heads=0 must be rejected by the
        # attention layer's validation, not silently become MHA
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)


def gpt3_1p3b(**overrides) -> "GPTConfig":
    """GPT-3 XL / 1.3B: 24 layers, d=2048, 16 heads."""
    return GPTConfig(**{**dict(hidden_size=2048, num_layers=24, num_heads=16),
                        **overrides})


def gpt_tiny(**overrides) -> "GPTConfig":
    return GPTConfig(**{**dict(vocab_size=1024, hidden_size=128, num_layers=2,
                               num_heads=4, max_position_embeddings=256),
                        **overrides})


def _cp_active() -> bool:
    from ...distributed.topology import get_hybrid_mesh
    mesh = get_hybrid_mesh()
    return mesh is not None and mesh.shape.get("sep", 1) > 1


def _init_attr(cfg: GPTConfig, spec=None) -> ParamAttr:
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range),
                     partition_spec=spec)


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.kv_heads = cfg.kv_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        if self.kv_heads < 1 or self.num_heads % self.kv_heads:
            raise ValueError(
                f"num_heads ({self.num_heads}) must be a multiple of "
                f"num_kv_heads ({self.kv_heads})")
        # GSPMD shards the kv-head axis over mp: kv_heads % mp != 0 is
        # correct but silently uneven (idle shards + implicit resharding),
        # so surface it — a warning, since replicate-KV setups are legal.
        from ...distributed.topology import get_hybrid_mesh
        mesh = get_hybrid_mesh()
        if mesh is not None and "mp" in mesh.axis_names:
            mp = mesh.shape["mp"]
            if mp > 1 and self.kv_heads % mp:
                import warnings
                warnings.warn(
                    f"num_kv_heads ({self.kv_heads}) is not divisible by the "
                    f"mp mesh degree ({mp}): GSPMD shards the KV-head axis "
                    f"unevenly (idle shards + implicit resharding). Use a "
                    f"kv_heads multiple of mp, or lower mp.", UserWarning)
        h = cfg.hidden_size
        if self.kv_heads == self.num_heads:
            self.qkv_proj = ColumnParallelLinear(
                h, 3 * h, weight_attr=_init_attr(cfg), has_bias=True,
                gather_output=False)
        else:
            self.q_proj = ColumnParallelLinear(
                h, h, weight_attr=_init_attr(cfg), has_bias=True,
                gather_output=False)
            self.kv_proj = ColumnParallelLinear(
                h, 2 * self.kv_heads * self.head_dim,
                weight_attr=_init_attr(cfg), has_bias=True,
                gather_output=False)
        self.out_proj = RowParallelLinear(
            h, h, weight_attr=_init_attr(cfg), has_bias=True,
            input_is_parallel=True)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def _project_qkv(self, x):
        """-> q [b,s,H,D], k/v [b,s,KH,D], heads sharded over mp."""
        b, s, _ = x.shape
        # batch/seq dims stay UNCONSTRAINED: pinning them replicated forces
        # a replicate-then-repartition when the incoming activation is
        # dp/sep-sharded (SPMD involuntary-remat warning, dryrun[8])
        U = P.UNCONSTRAINED
        if self.kv_heads == self.num_heads:
            qkv = self.qkv_proj(x)  # [b, s, 3h] (h sharded over mp)
            qkv = qkv.reshape(b, s, 3, self.num_heads, self.head_dim)
            qkv = _constrain(qkv, P(U, U, U, MP_AXIS, U))
            return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = self.q_proj(x).reshape(b, s, self.num_heads, self.head_dim)
        q = _constrain(q, P(U, U, MP_AXIS, U))
        kv = self.kv_proj(x).reshape(b, s, 2, self.kv_heads, self.head_dim)
        kv = _constrain(kv, P(U, U, U, MP_AXIS, U))
        return q, kv[:, :, 0], kv[:, :, 1]

    def _repeat_kv(self, k, v):
        rep = self.num_heads // self.kv_heads
        if rep == 1:
            return k, v
        return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)

    def forward(self, x):
        b, s, h = x.shape
        q, k, v = self._project_qkv(x)
        if self.cfg.context_parallel and _cp_active():
            from ...distributed.context_parallel import (ring_attention,
                                                         ulysses_attention)
            if self.cfg.context_parallel not in ("ring", "ulysses"):
                raise ValueError(
                    f"context_parallel={self.cfg.context_parallel!r}; "
                    "expected 'ring' or 'ulysses'")
            if self.cfg.attention_dropout > 0.0 and self.training:
                raise NotImplementedError(
                    "attention_dropout > 0 is not supported with context "
                    "parallelism (probs are never materialized globally)")
            if self.cfg.context_parallel == "ring":
                # ring's block attention contracts equal head counts;
                # broadcast grouped KV for it only.
                out = ring_attention(q, *self._repeat_kv(k, v), causal=True)
            else:
                # ulysses repeats KV just enough for the head all-to-all —
                # pass the grouped tensors through untouched.
                out = ulysses_attention(q, k, v, causal=True)
        elif self.cfg.use_flash_attention:
            # flash handles grouped KV natively (index-mapped tiles)
            out = flash_attention(q, k, v, dropout=self.cfg.attention_dropout,
                                  causal=True, training=self.training)
        else:
            out = F.scaled_dot_product_attention(
                q, *self._repeat_kv(k, v), is_causal=True,
                dropout_p=self.cfg.attention_dropout,
                training=self.training)
        out = out.reshape(b, s, h)
        out = self.out_proj(out)
        return self.dropout(out)

    def decode(self, x, cache, offset):
        """Incremental attention with a KV cache.

        x: [b, s, h] new tokens (s = prompt len at prefill, 1 per decode
        step); cache: (k, v) each [b, max_len, heads, head_dim]; offset:
        traced scalar — how many positions are already cached. Returns
        (out [b, s, h], new_cache). The cache is written with
        dynamic_update_slice (traced offsets compose with lax.scan), and
        attention masks keys past offset+s plus intra-block causality.
        """
        b, s, h = x.shape
        q, k, v = self._project_qkv(x)
        k_cache, v_cache = cache                     # [b, max, KH, D]
        k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, offset, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, offset, 0, 0))
        max_len = k_cache.shape[1]
        q_pos = offset + jnp.arange(s)              # [s]
        k_pos = jnp.arange(max_len)                 # [max_len]
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]  # [1,1,s,max]
        out = F.scaled_dot_product_attention(
            q, *self._repeat_kv(k_cache, v_cache), attn_mask=mask,
            is_causal=False, training=False)
        out = self.out_proj(out.reshape(b, s, h))
        return out, (k_cache, v_cache)


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.up = ColumnParallelLinear(cfg.hidden_size, cfg.ffn_size,
                                       weight_attr=_init_attr(cfg),
                                       gather_output=False)
        self.down = RowParallelLinear(cfg.ffn_size, cfg.hidden_size,
                                      weight_attr=_init_attr(cfg),
                                      input_is_parallel=True)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, x):
        x = self.up(x)
        x = F.gelu(x, approximate=True)
        x = self.down(x)
        return self.dropout(x)


class GPTBlock(nn.Layer):
    """Pre-LN decoder block."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.mlp = GPTMLP(cfg)

    def _inner(self, x):
        if self.cfg.sequence_parallel:
            from ...distributed.fleet.utils.sequence_parallel_utils import \
                sequence_parallel_constraint
            x = sequence_parallel_constraint(x)
        if self.cfg.context_parallel and _cp_active():
            # Keep activations sequence-sharded over sep between blocks.
            x = _constrain(x, P(None, "sep", None))
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x

    def forward(self, x):
        if self.cfg.recompute and self.training:
            # Policy swept on the 1.3B shape (r3/r4): full recompute
            # (dots_with_no_batch_dims_saveable) costs ~25% step time;
            # saving fwd matmul outputs (dots_saveable) trades ~290 MB/
            # layer of bf16 activations for most of that time back — and
            # additionally saving the flash kernel's (o, lse) residuals
            # plus LayerNorm outputs (dots_and_flash_saveable) skips the
            # in-backward flash re-run (~1 ms/layer) and LN recomputes
            # (~1.6 ms each) for ≈ +98 MB/layer. The BASELINE layout
            # (mp=4) quarters the per-chip share.
            from ...distributed.fleet.utils.recompute import RecomputePolicy
            policy = RecomputePolicy.resolve(self.cfg.recompute_policy)
            return jax.checkpoint(self._inner, policy=policy)(x)
        return self._inner(x)

    def decode(self, x, cache, offset):
        attn_out, cache = self.attn.decode(self.ln_1(x), cache, offset)
        x = x + attn_out
        x = x + self.mlp(self.ln_2(x))
        return x, cache


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=_init_attr(cfg, P(MP_AXIS, None)))
        self.wpe = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size,
            weight_attr=_init_attr(cfg))
        self.drop = nn.Dropout(cfg.hidden_dropout)
        self.h = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for block in self.h:
            x = block(x)
        return self.ln_f(x)

    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        head_dim = self.cfg.hidden_size // self.cfg.num_heads
        shape = (batch, max_len, self.cfg.kv_heads, head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in self.h]

    def decode(self, input_ids, caches, offset):
        """Forward with KV caches. input_ids [b, s]; offset = number of
        already-cached positions (traced). Returns (hidden, new_caches)."""
        b, s = input_ids.shape
        pos = offset + jnp.arange(s)[None, :]
        x = self.wte(input_ids) + self.wpe(pos)
        new_caches = []
        for block, cache in zip(self.h, caches):
            x, cache = block.decode(x, cache, offset)
            new_caches.append(cache)
        return self.ln_f(x), new_caches


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPT(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, weight_attr=_init_attr(cfg),
                has_bias=False, gather_output=False)
        self.loss_fn = ParallelCrossEntropy()

    def logits(self, hidden):
        if self.cfg.tie_word_embeddings:
            w = self.gpt.wte.weight  # [vocab(mp-sharded), hidden]
            logits = jnp.matmul(hidden, w.T)
            return _constrain(logits, P(None, None, MP_AXIS))
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None):
        hidden = self.gpt(input_ids)
        logits = self.logits(hidden)
        if labels is None:
            return logits
        loss = self.loss_fn(logits, labels)
        return jnp.mean(loss)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0):
        """Autoregressive decoding with a KV cache
        (ref paddlenlp-style generate; decode loop is one lax.scan —
        compiled once, MXU matmuls per step).

        Returns [b, prompt_len + max_new_tokens] token ids; positions after
        an emitted eos are padded with eos.
        """
        input_ids = jnp.asarray(input_ids)
        b, prompt_len = input_ids.shape
        total = prompt_len + max_new_tokens
        if total > self.cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {prompt_len} + max_new_tokens {max_new_tokens} "
                f"exceeds max_position_embeddings "
                f"{self.cfg.max_position_embeddings}")
        if max_new_tokens <= 0:
            return input_ids
        was_training = self.training
        self.eval()  # dropout must be off in the decode loop
        # Cache dtype must match the activations (bf16 under AMP O2).
        act_dtype = self.gpt.wte.weight.dtype
        caches = self.gpt.init_cache(b, total, dtype=act_dtype)
        hidden, caches = self.gpt.decode(input_ids, caches, 0)
        key = jax.random.PRNGKey(seed)

        def pick(logits, key):
            logits = logits / jnp.maximum(temperature, 1e-6)
            if not do_sample:
                return jnp.argmax(logits, axis=-1)
            if top_k:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
                probs = jax.nn.softmax(sorted_logits, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # smallest set with cumulative prob >= top_p (keep the
                # first token crossing the threshold)
                cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
                cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx,
                                             axis=-1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1)

        key, sub = jax.random.split(key)
        next_tok = pick(self.logits(hidden[:, -1:])[:, 0], sub)
        finished = (next_tok == eos_token_id) \
            if eos_token_id is not None else None

        def step(carry, _):
            caches, tok, offset, key, finished = carry
            hidden, caches = self.gpt.decode(tok[:, None], caches, offset)
            key, sub = jax.random.split(key)
            nxt = pick(self.logits(hidden)[:, 0], sub)
            if finished is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            return (caches, nxt, offset + 1, key, finished), nxt

        if max_new_tokens > 1:
            (_, _, _, _, _), rest = jax.lax.scan(
                step, (caches, next_tok, jnp.asarray(prompt_len), key,
                       finished),
                None, length=max_new_tokens - 1)
            rest = jnp.swapaxes(rest, 0, 1)  # [b, T-1]
            out = jnp.concatenate([input_ids, next_tok[:, None], rest],
                                  axis=1)
        else:
            out = jnp.concatenate([input_ids, next_tok[:, None]], axis=1)
        if was_training:
            self.train()
        return out
