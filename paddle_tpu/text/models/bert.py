"""BERT (BASELINE config 3: BERT-base pretraining under AMP O2).

Encoder-only transformer with MLM + NSP heads, built from the same TP-capable
blocks as GPT (reference analog: paddlenlp-style BERT assembled from
nn.TransformerEncoder; pretraining heads per the fleet AMP tests)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import ParamAttr

__all__ = ["BertConfig", "Bert", "BertForPretraining", "bert_base", "bert_tiny"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02


def bert_base(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def bert_tiny(**overrides) -> BertConfig:
    return BertConfig(**{**dict(vocab_size=1024, hidden_size=128, num_layers=2,
                                num_heads=4, intermediate_size=512,
                                max_position_embeddings=128), **overrides})


def _attr(cfg) -> ParamAttr:
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=_attr(cfg))
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size,
                                                weight_attr=_attr(cfg))
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size,
                                                  weight_attr=_attr(cfg))
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_epsilon)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = jnp.arange(s)[None, :]
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class Bert(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.encoder = nn.TransformerEncoder(
            lambda: nn.TransformerEncoderLayer(
                cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
                dropout=cfg.hidden_dropout, activation="gelu",
                attn_dropout=cfg.attention_dropout,
                weight_attr=_attr(cfg)),
            cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=_attr(cfg))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                packed_segment_ids=None):
        """``packed_segment_ids`` [B, S] int32 activates PACKED attention:
        multiple sequences share a row, attention stays within segments
        (flash_attn_unpadded's varlen semantics on static shapes)."""
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]; key-only masks ride the
            # flash kernel's additive key-bias block (nn.functional SDPA)
            mask = (1.0 - attention_mask[:, None, None, :].astype(x.dtype)) * -1e9
        x = self.encoder(x, src_mask=mask, segment_ids=packed_segment_ids)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (loss as in the reference's pretraining tests)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = Bert(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                       weight_attr=_attr(cfg))
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_epsilon)
        self.mlm_bias = self.create_parameter(
            (cfg.vocab_size,), is_bias=True)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2, weight_attr=_attr(cfg))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None,
                packed_segment_ids=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                                packed_segment_ids=packed_segment_ids)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        logits = jnp.matmul(h, self.bert.embeddings.word_embeddings.weight.T) \
            + self.mlm_bias
        nsp_logits = self.nsp_head(pooled)
        if masked_lm_labels is None:
            return logits, nsp_logits
        mlm_loss = F.cross_entropy(logits, masked_lm_labels,
                                   ignore_index=-100, reduction="mean")
        total = mlm_loss
        if next_sentence_labels is not None:
            total = total + F.cross_entropy(nsp_logits,
                                            next_sentence_labels.reshape(-1),
                                            reduction="mean")
        return total
