"""Reader decorators (``paddle.reader`` parity).

Reference: ``python/paddle/reader/decorator.py`` — composable generator
transforms predating DataLoader (shuffle/buffered/chain/compose/cache/
firstn/map_readers/xmap_readers). The buffered/xmap variants use a
background thread pool feeding a queue, same shape as the reference's
implementation but without its multiprocess plumbing (the heavy path in
this build is ``paddle_tpu.io.DataLoader``'s native shared-memory workers).
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers"]


def cache(reader):
    """Materialize the reader once; replay from memory afterwards."""
    data = []
    filled = threading.Event()
    lock = threading.Lock()

    def cached():
        with lock:
            if not filled.is_set():
                data.clear()  # discard partial fill from a failed attempt
                data.extend(reader())
                filled.set()
        return iter(data)

    return cached


def map_readers(func, *readers):
    """Zip several readers and map ``func`` over the sample tuples."""

    def reader():
        for args in zip(*[r() for r in readers]):
            yield func(*args)

    return reader


def shuffle(reader, buf_size: int):
    """Shuffle within a sliding buffer of ``buf_size`` samples."""

    def shuffled():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers end to end."""

    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment: bool = True):
    """Read in lockstep, yielding flattened tuples of parallel samples."""

    def flatten(sample):
        out = []
        for item in sample:
            if isinstance(item, tuple):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)

    def composed():
        iters = [r() for r in readers]
        if check_alignment:
            for samples in itertools.zip_longest(*iters):
                if any(s is None for s in samples):
                    raise RuntimeError("composed readers have different "
                                       "lengths")
                yield flatten(samples)
        else:
            for samples in zip(*iters):
                yield flatten(samples)

    return composed


def firstn(reader, n: int):
    """Only the first ``n`` samples."""

    def limited():
        return itertools.islice(reader(), n)

    return limited


_END = object()


class _Raise:
    """Producer-side exception carrier: re-raised in the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def buffered(reader, size: int):
    """Decouple producer/consumer through a ``size``-bounded queue filled by
    a daemon thread. Producer exceptions are forwarded and re-raised in the
    consumer rather than truncating the stream."""

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:  # noqa: BLE001 — forwarded, not dropped
                q.put(_Raise(e))
                return
            q.put(_END)

        threading.Thread(target=fill, daemon=True).start()
        while True:
            sample = q.get()
            if sample is _END:
                return
            if isinstance(sample, _Raise):
                raise sample.exc
            yield sample

    return buffered_reader


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Map ``mapper`` over the reader with ``process_num`` worker threads.

    ``order=True`` preserves input order by tagging samples with sequence
    numbers and releasing them in order.
    """

    def ordered_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:  # noqa: BLE001 — forwarded below
                out_q.put(_Raise(e))
            finally:
                for _ in range(process_num):
                    in_q.put(_END)

        def work():
            while True:
                item = in_q.get()
                if item is _END:
                    out_q.put(_END)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as e:  # noqa: BLE001 — forwarded below
                    out_q.put(_Raise(e))
                    out_q.put(_END)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        done = 0
        if order:
            pending = {}
            expect = 0
            while done < process_num:
                item = out_q.get()
                if item is _END:
                    done += 1
                    continue
                if isinstance(item, _Raise):
                    raise item.exc
                i, mapped = item
                pending[i] = mapped
                while expect in pending:
                    yield pending.pop(expect)
                    expect += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while done < process_num:
                item = out_q.get()
                if item is _END:
                    done += 1
                    continue
                if isinstance(item, _Raise):
                    raise item.exc
                yield item[1]

    return ordered_reader
