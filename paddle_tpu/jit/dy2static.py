"""dy2static: AST conversion of data-dependent Python control flow.

The reference rewrites Python ``if``/``while``/``for`` over tensors into
static-graph control-flow ops by AST transformation
(``python/paddle/jit/dy2static/ifelse_transformer.py:1``,
``loop_transformer.py``, driven by ``program_translator.py:313``). Pure
tracing — the default JAX conversion — cannot handle a branch on a traced
value. This module is the TPU-native form of those transformers: the same
source rewrite, but the hoisted branch/loop functions dispatch to
``lax.cond`` / ``lax.while_loop`` / ``lax.fori_loop`` when the condition is
a tracer, and run plain Python otherwise (so converted functions behave
identically outside jit).

What converts:

- ``if``/``elif``/``else`` over tensor conditions → ``lax.cond`` with the
  branch-assigned variables as carried operands (write-set analysis, like
  the reference's ``NameVisitor``);
- ``while`` over tensor conditions → ``lax.while_loop``;
- ``for i in range(...)`` with traced bounds → ``lax.fori_loop``;
- ``and`` / ``or`` / ``not`` over tensors → ``jnp.logical_*`` (both sides
  evaluate — short-circuit semantics are Python-only).

- ``break``/``continue`` → loop-carried boolean guard flags (ref
  ``jit/dy2static/break_continue_transformer.py``): the flag is set where
  the statement stood, every later statement is guarded by ``not flag``,
  a ``while`` test gains ``and not break_flag``, and a ``for`` body is
  fully guarded (remaining fori iterations become no-ops);
- early ``return`` → return-flag + return-value variables (ref
  ``early_return_transformer.py`` / ``return_transformer.py``); the
  return-value slot starts as ``None`` and is materialized to zeros of the
  other branch's abstract shape inside ``lax.cond``/``lax.while_loop``
  (only for generated ``__jst_rv_*`` names — user variables assigned in
  one branch still raise the structural error);
- ``assert`` → runtime check via ``jax.debug.callback`` when traced (ref
  ``assert_transformer.py``);
- ``int()``/``float()``/``bool()``/``len()`` on traced tensors → dtype
  casts / shape reads (ref ``cast_transformer.py``).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, List, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["convert_to_static", "Undefined", "UNDEFINED",
           "convert_ifelse", "convert_while", "convert_for_range",
           "convert_logical_and", "convert_logical_or", "convert_logical_not",
           "convert_assert", "convert_len", "convert_int", "convert_float",
           "convert_bool"]


class Undefined:
    """Sentinel for a name assigned in only one branch (ref dy2static
    UndefinedVar). Using it under a tensor condition is an error; under a
    Python condition it simply never escapes the taken branch."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<dy2static undefined>"


UNDEFINED = Undefined()

# Undefined is an *empty static pytree* (the reference's UndefinedVar): it
# flattens to zero leaves, so an unread UNDEFINED operand costs lax.cond
# nothing, and a branch that fails to assign a name returns UNDEFINED whose
# treedef mismatches the other branch's array — a structural error exactly
# when the program is genuinely ill-formed.
jax.tree_util.register_pytree_node(
    Undefined, lambda u: ((), None), lambda aux, children: UNDEFINED)


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# Runtime converters (the generated code calls these)
# ---------------------------------------------------------------------------

def _no_leaves(x) -> bool:
    return len(jax.tree_util.tree_leaves(x)) == 0


def _spec_zeros(spec):
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype)
        if hasattr(s, "shape") and hasattr(s, "dtype")
        else jnp.zeros_like(jnp.asarray(s)), spec)


def _evalable(x):
    """eval_shape needs shape/dtype on every leaf; lift python scalars."""
    return jax.tree_util.tree_map(
        lambda l: l if hasattr(l, "shape") and hasattr(l, "dtype")
        else jnp.asarray(l), x)


def _materialize_undef(operands, out_spec, undef_ok):
    """Replace empty-pytree operands (None/UNDEFINED) in `undef_ok` slots
    with zeros of the loop body's abstract output — the return-value slot
    of a rewritten early return (never read while its flag is False)."""
    ops = list(operands)
    for i in undef_ok:
        if _no_leaves(ops[i]) and not _no_leaves(out_spec[i]):
            ops[i] = _spec_zeros(out_spec[i])
    return tuple(ops)


def convert_ifelse(cond, true_fn, false_fn, operands: tuple,
                   undef_ok: tuple = ()):
    """Dispatch an ``if``: lax.cond for traced conditions, Python otherwise."""
    if _is_traced(cond) or any(_is_traced(o) for o in operands):
        if not _is_traced(cond):
            # Concrete cond with traced operands: still take one branch
            # eagerly — matches Python semantics and avoids tracing both.
            return true_fn(*operands) if cond else false_fn(*operands)
        if undef_ok:
            ev = _evalable(operands)
            ot = jax.eval_shape(true_fn, *ev)
            of = jax.eval_shape(false_fn, *ev)

            def _fix(fn, mine, other):
                idxs = [i for i in undef_ok
                        if _no_leaves(mine[i]) and not _no_leaves(other[i])]
                if not idxs:
                    return fn

                def wrapped(*ops):
                    out = list(fn(*ops))
                    for i in idxs:
                        out[i] = _spec_zeros(other[i])
                    return tuple(out)
                return wrapped

            true_fn = _fix(true_fn, ot, of)
            false_fn = _fix(false_fn, of, ot)
        try:
            return lax.cond(cond, true_fn, false_fn, *operands)
        except TypeError as e:
            if "Undefined" in str(e) or "pytree" in str(e) or \
                    "structure" in str(e):
                raise ValueError(
                    "dy2static: a variable assigned in only one branch of a "
                    "tensor `if` is used afterwards; initialize it before "
                    "the branch so both lax.cond branches return the same "
                    "structure") from e
            raise
    return true_fn(*operands) if cond else false_fn(*operands)


def convert_while(cond_fn, body_fn, operands: tuple, undef_ok: tuple = ()):
    """Dispatch a ``while``: lax.while_loop when the condition traces."""
    probe = cond_fn(*operands)
    if _is_traced(probe) or any(_is_traced(o) for o in operands):
        if undef_ok:
            out_spec = jax.eval_shape(body_fn, *_evalable(operands))
            operands = _materialize_undef(operands, out_spec, undef_ok)
        for o in operands:
            if o is UNDEFINED:
                raise ValueError(
                    "dy2static: initialize every loop variable before a "
                    "tensor `while` loop (a name assigned in the loop body "
                    "has no value on entry)")
        return lax.while_loop(lambda c: cond_fn(*c), lambda c: body_fn(*c),
                              operands)
    while probe:
        operands = body_fn(*operands)
        probe = cond_fn(*operands)
    return operands


def convert_for_range(start, stop, step, body_fn, operands: tuple,
                      undef_ok: tuple = ()):
    """Dispatch ``for i in range(...)``: lax.fori_loop (step 1, traced
    bounds) / lax.while_loop (general step) / Python range otherwise."""
    traced = any(_is_traced(x) for x in (start, stop, step)) or \
        any(_is_traced(o) for o in operands)
    if traced:
        if undef_ok:
            i_spec = jnp.asarray(start)
            out_spec = jax.eval_shape(
                lambda i, *ops: body_fn(i, *ops), i_spec,
                *_evalable(operands))
            operands = _materialize_undef(operands, out_spec, undef_ok)
        for o in operands:
            if o is UNDEFINED:
                raise ValueError(
                    "dy2static: initialize every loop variable before a "
                    "traced `for` loop")
        if isinstance(step, int) and step == 1:
            return lax.fori_loop(start, stop,
                                 lambda i, c: body_fn(i, *c), operands)
        i0 = jnp.asarray(start)

        def cond(c):
            i = c[0]
            return jnp.where(step > 0, i < stop, i > stop)

        def body(c):
            i, rest = c[0], c[1:]
            return (i + step,) + tuple(body_fn(i, *rest))

        return lax.while_loop(cond, body, (i0,) + tuple(operands))[1:]
    for i in range(start, stop, step):
        operands = tuple(body_fn(i, *operands))
    return operands


def convert_logical_and(lhs, rhs_fn):
    if _is_traced(lhs) or isinstance(lhs, jax.Array):
        return jnp.logical_and(lhs, rhs_fn())
    return lhs and rhs_fn()


def convert_logical_or(lhs, rhs_fn):
    if _is_traced(lhs) or isinstance(lhs, jax.Array):
        return jnp.logical_or(lhs, rhs_fn())
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_traced(x) or isinstance(x, jax.Array):
        return jnp.logical_not(x)
    return not x


def resolve_return(v):
    """Final value of a rewritten function: the UNDEFINED placeholder means
    no `return` statement ever fired — Python's implicit None."""
    return None if v is UNDEFINED else v


def concrete_true(x) -> bool:
    """True only for a CONCRETE truthy flag — used to really `break` out of
    python-iterated loops; traced flags fall back to guarded no-ops."""
    return (not _is_traced(x)) and bool(x)


def convert_assert(cond, msg=None):
    """``assert`` over a traced condition (ref assert_transformer.py →
    static Assert op): checked at run time via a host callback."""
    if _is_traced(cond):
        def _check(c):
            if not bool(c):
                raise AssertionError(
                    msg if msg is not None else "dy2static assert failed")
        jax.debug.callback(_check, cond)
        return
    assert cond, msg


def convert_len(x):
    if _is_traced(x) or isinstance(x, jax.Array):
        return x.shape[0]
    return len(x)


def convert_int(x):
    if _is_traced(x) or isinstance(x, jax.Array):
        return jnp.asarray(x).astype(jnp.int32)
    return int(x)


def convert_float(x):
    if _is_traced(x) or isinstance(x, jax.Array):
        from ..core.dtype import get_default_dtype
        return jnp.asarray(x).astype(get_default_dtype())
    return float(x)


def convert_bool(x):
    if _is_traced(x) or isinstance(x, jax.Array):
        return jnp.asarray(x).astype(jnp.bool_)
    return bool(x)


# ---------------------------------------------------------------------------
# Static analysis helpers (ref dy2static NameVisitor)
# ---------------------------------------------------------------------------

def _assigned_names(nodes: Sequence[ast.stmt]) -> List[str]:
    """Names bound by assignment anywhere in `nodes` (order-stable)."""
    out: List[str] = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    node.id not in out:
                out.append(node.id)

        def visit_FunctionDef(self, node):  # don't descend into nested defs
            if node.name not in out:
                out.append(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    for n in nodes:
        V().visit(n)
    return out


def _read_names(nodes: Sequence[ast.AST]) -> Set[str]:
    out: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                out.add(node.id)

    for n in nodes:
        V().visit(n)
    return out


def _contains(nodes: Sequence[ast.stmt], kinds) -> bool:
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, kinds):
                return True
    return False


def _iter_owned_break_continue(body: Sequence[ast.stmt]):
    """Yield Break/Continue nodes belonging to THIS loop body — nested
    loops own theirs, nested function defs are separate scopes. The SINGLE
    ownership walker: the rewriter (collect) and the converters'
    leave-eager guards (test) must agree on ownership."""
    for s in body:
        if isinstance(s, (ast.While, ast.For, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            continue
        if isinstance(s, (ast.Break, ast.Continue)):
            yield s
            continue
        for fld in ("body", "orelse", "finalbody"):
            sub = getattr(s, fld, None)
            if sub:
                yield from _iter_owned_break_continue(sub)
        if isinstance(s, ast.Try):
            for h in s.handlers:
                yield from _iter_owned_break_continue(h.body)


def _owned_break_continue(body: Sequence[ast.stmt]) -> bool:
    return any(True for _ in _iter_owned_break_continue(body))


def _has_top_level_return(nodes: Sequence[ast.stmt]) -> bool:
    """Return statements excluding those inside nested function defs."""
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(sub, ast.Return):
                return True
    return False


_CTR = [0]


def _fresh(prefix: str) -> str:
    _CTR[0] += 1
    return f"__jst_{prefix}_{_CTR[0]}"


_FN_PREFIXES = ("__jst_true_fn", "__jst_false_fn", "__jst_cond_fn",
                "__jst_body_fn", "__jst_for_body")


class _GeneratedNames:
    """`some_set - _GENERATED` filters out generated helper FUNCTION names,
    which must never join a carried-variable set. Generated DATA names
    (break/continue/return flags, return values) stay carried."""

    def __rsub__(self, other):
        return {n for n in other if not n.startswith(_FN_PREFIXES)}


_GENERATED = _GeneratedNames()


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _written_before_read(stmts: Sequence[ast.stmt], name: str,
                         pre_reads: Sequence[ast.AST] = ()) -> bool:
    """True when `name` is unconditionally assigned at the top level of
    `stmts` before any possible read — its entry value is provably dead, so
    a zeros placeholder is safe for the loop-carry."""
    if name in _read_names(list(pre_reads)):
        return False
    for s in stmts:
        reads = _read_names([s])
        if isinstance(s, ast.Assign) and name not in reads and any(
                isinstance(t, ast.Name) and t.id == name for t in s.targets):
            return True
        if name in reads:
            return False
        if name in _assigned_names([s]):
            return False  # conditional / compound write
    return False


def _undef_ok_kw(carried: Sequence[str], body: Sequence[ast.stmt] = (),
                 pre_reads: Sequence[ast.AST] = ()) -> List[ast.keyword]:
    """keyword for carried slots whose entry value may be a None/UNDEFINED
    placeholder materialized to zeros: generated return-value vars, plus
    user vars provably written before read in the loop body."""
    idxs = [i for i, c in enumerate(carried)
            if c.startswith("__jst_rv")
            or (body and _written_before_read(body, c, pre_reads))]
    if not idxs:
        return []
    return [ast.keyword(arg="undef_ok", value=ast.Tuple(
        elts=[ast.Constant(value=i) for i in idxs], ctx=ast.Load()))]


def _undefined_default(names: Sequence[str]) -> List[ast.stmt]:
    """`name = __jst.UNDEFINED if '<name>' not in dir() else name` — cheaper:
    we emit  try/except NameError guards so names missing on entry carry the
    sentinel. Generated guard flags default to False (they are always
    re-initialized before being read) and return-value slots to None, so an
    inner rewritten loop composes with an enclosing converted loop."""
    stmts = []
    for nm in names:
        if nm.startswith(("__jst_brk", "__jst_cont", "__jst_rf")):
            default: ast.expr = ast.Constant(value=False)
        else:
            default = ast.Attribute(value=_name("__jst"), attr="UNDEFINED",
                                    ctx=ast.Load())
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[_name(nm, ast.Store())],
                             value=_name(nm))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_name("NameError"),
                                     _name("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[_name(nm, ast.Store())], value=default)])],
            orelse=[], finalbody=[]))
    return stmts


def _assign(name: str, value: ast.expr) -> ast.stmt:
    return ast.Assign(targets=[_name(name, ast.Store())], value=value)


def _const(v) -> ast.expr:
    return ast.Constant(value=v)


def _not_flags(flags: Sequence[str]) -> ast.expr:
    """`not (f1 or f2 or ...)` — converted later by the BoolOp/Not visitors
    so it works for both python and traced flags."""
    test: ast.expr = _name(flags[0])
    for f in flags[1:]:
        test = ast.BoolOp(op=ast.Or(), values=[test, _name(f)])
    return ast.UnaryOp(op=ast.Not(), operand=test)


class _BreakContinueRewriter(ast.NodeTransformer):
    """break/continue → loop-carried guard flags (ref
    break_continue_transformer.py).

    Runs BEFORE the control-flow transformer: the output is flag-based pure
    Python, which the main pass then lowers (flag `if`s → lax.cond, the
    augmented `while` test → lax.while_loop condition). A `for range` loop
    keeps its trip count — iterations after a `break` are fully guarded
    no-ops, which is exactly the lax.fori_loop-compatible lowering.
    """

    def _loop_stmts(self, body: Sequence[ast.stmt], kinds):
        """Break/Continue nodes belonging to THIS loop (nested loops were
        already rewritten bottom-up, and python-only nested loops own their
        own break/continue). Shares the ownership walker with the
        converters' leave-eager guards."""
        return [s for s in _iter_owned_break_continue(body)
                if isinstance(s, kinds)]

    def _process(self, stmts, bflag, cflag, flags):
        """Replace break/continue with flag sets; guard trailing statements
        at every nesting level. Returns (new_stmts, may_set_flag)."""
        out: List[ast.stmt] = []
        for idx, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(_assign(bflag, _const(True)))
                return out, True
            if isinstance(s, ast.Continue):
                out.append(_assign(cflag, _const(True)))
                return out, True
            may = False
            if isinstance(s, ast.If):
                nb, mb = self._process(s.body, bflag, cflag, flags)
                no, mo = self._process(s.orelse, bflag, cflag, flags)
                s.body = nb or [ast.Pass()]
                s.orelse = no
                may = mb or mo
            out.append(s)
            if may:
                rest, _ = self._process(stmts[idx + 1:], bflag, cflag, flags)
                if rest:
                    guard = ast.If(test=_not_flags(flags), body=rest,
                                   orelse=[])
                    out.append(guard)
                return out, True
        return out, False

    def _rewrite_loop(self, node):
        self.generic_visit(node)
        owned = self._loop_stmts(node.body, (ast.Break, ast.Continue))
        if not owned:
            return node
        has_break = any(isinstance(s, ast.Break) for s in owned)
        has_cont = any(isinstance(s, ast.Continue) for s in owned)
        bflag = _fresh("brk") if has_break else _fresh("brk_unused")
        cflag = _fresh("cont") if has_cont else _fresh("cont_unused")
        flags = ([bflag] if has_break else []) + \
            ([cflag] if has_cont else [])
        body, _ = self._process(node.body, bflag, cflag, flags)
        pre: List[ast.stmt] = []
        if has_break:
            pre.append(_assign(bflag, _const(False)))
        if has_cont:
            # the flag is reset at each iteration start, but it is also a
            # loop-carried operand, so it needs a pre-loop binding
            pre.append(_assign(cflag, _const(False)))
        reset = [_assign(cflag, _const(False))] if has_cont else []
        if isinstance(node, ast.While):
            node.body = reset + body
            if has_break:
                node.test = ast.BoolOp(
                    op=ast.And(),
                    values=[ast.UnaryOp(op=ast.Not(), operand=_name(bflag)),
                            node.test])
        else:  # For: guard whole body; trip count is preserved
            inner = reset + body
            if has_break:
                guarded = [ast.If(test=ast.UnaryOp(op=ast.Not(),
                                                   operand=_name(bflag)),
                                  body=inner, orelse=[])]
                is_range = (isinstance(node.iter, ast.Call)
                            and isinstance(node.iter.func, ast.Name)
                            and node.iter.func.id == "range")
                if not is_range:
                    # python-iterated loop: a concrete break flag should
                    # actually stop the iterator, not no-op through it
                    guarded.insert(0, ast.If(
                        test=ast.Call(
                            func=ast.Attribute(value=_name("__jst"),
                                               attr="concrete_true",
                                               ctx=ast.Load()),
                            args=[_name(bflag)], keywords=[]),
                        body=[ast.Break()], orelse=[]))
                node.body = guarded
            else:
                node.body = inner
        out = pre + [node]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    visit_While = _rewrite_loop
    visit_For = _rewrite_loop


def _contains_return(node_or_list) -> bool:
    nodes = node_or_list if isinstance(node_or_list, list) else [node_or_list]
    return _has_top_level_return(nodes)


def _returns_ok(stmts: Sequence[ast.stmt]) -> bool:
    """True when every return is in tail position (the form the plain
    if-transformer already supports) — no rewrite needed."""
    if not stmts:
        return True
    for s in stmts[:-1]:
        if _contains_return([s]):
            return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        b, o = _contains_return(last.body), _contains_return(last.orelse)
        if not (b or o):
            return True
        if b != o:
            return False  # single-branch tail return = early return
        return _returns_ok(last.body) and _returns_ok(last.orelse)
    return not _contains_return([last])


class _ReturnRewriter:
    """Early returns → return-flag + return-value vars (ref
    early_return_transformer.py / return_transformer.py). Applied to the
    top-level function only; the value var is named ``__jst_rv_*`` so the
    lax converters may materialize its None placeholder as zeros."""

    def rewrite(self, fdef):
        if _returns_ok(fdef.body):
            return
        self.rf = _fresh("rf")
        self.rv = _fresh("rv")
        body, _ = self._process(fdef.body)
        # rv starts as the UNDEFINED placeholder (NOT None): an explicit
        # user `return None` assigns real None, which then structurally
        # mismatches an array-returning branch instead of being silently
        # materialized to zeros; the final resolve maps a never-fired
        # placeholder back to Python's implicit None.
        undef = ast.Attribute(value=_name("__jst"), attr="UNDEFINED",
                              ctx=ast.Load())
        resolve = ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="resolve_return",
                               ctx=ast.Load()),
            args=[_name(self.rv)], keywords=[])
        fdef.body = ([_assign(self.rf, _const(False)),
                      _assign(self.rv, undef)] + body +
                     [ast.Return(value=resolve)])
        for s in fdef.body:
            ast.copy_location(s, fdef)
            ast.fix_missing_locations(s)

    def _process(self, stmts) -> Tuple[List[ast.stmt], bool]:
        out: List[ast.stmt] = []
        for idx, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                out.append(_assign(self.rf, _const(True)))
                out.append(_assign(self.rv,
                                   s.value if s.value is not None
                                   else _const(None)))
                return out, True
            may = False
            if isinstance(s, ast.If):
                nb, mb = self._process(s.body)
                no, mo = self._process(s.orelse)
                s.body = nb or [ast.Pass()]
                s.orelse = no
                may = mb or mo
            elif isinstance(s, ast.While):
                nb, mb = self._process(s.body)
                if mb:
                    s.body = nb
                    s.test = ast.BoolOp(
                        op=ast.And(),
                        values=[ast.UnaryOp(op=ast.Not(),
                                            operand=_name(self.rf)),
                                s.test])
                    may = True
            elif isinstance(s, ast.For):
                nb, mb = self._process(s.body)
                if mb:
                    s.body = [ast.If(
                        test=ast.UnaryOp(op=ast.Not(),
                                         operand=_name(self.rf)),
                        body=nb, orelse=[])]
                    may = True
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                pass  # nested scopes own their returns
            elif _contains_return([s]):
                raise NotImplementedError(
                    "dy2static: `return` inside "
                    f"{type(s).__name__} is not convertible")
            out.append(s)
            if may:
                rest, _ = self._process(stmts[idx + 1:])
                if rest:
                    out.append(ast.If(test=ast.UnaryOp(
                        op=ast.Not(), operand=_name(self.rf)),
                        body=rest, orelse=[]))
                return out, True
        return out, False


class _ControlFlowTransformer(ast.NodeTransformer):
    """Bottom-up rewrite of if/while/for-range/boolops into __jst calls."""

    # -- if / elif / else ---------------------------------------------------

    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        if _owned_break_continue(node.body) or \
                _owned_break_continue(node.orelse or []):
            # a residual python break/continue (e.g. the concrete-break
            # shim in python-iterated loops) cannot move into a hoisted
            # branch function — leave the `if` eager
            return node
        body, orelse = node.body, node.orelse or [ast.Pass()]
        t_ret = _has_top_level_return(body)
        f_ret = _has_top_level_return(orelse)
        if t_ret or f_ret:
            # Only the simple total form converts: each branch is exactly
            # one final `return <expr>` (possibly after other statements,
            # none of which return).
            def _tail_return_only(stmts):
                return (stmts and isinstance(stmts[-1], ast.Return)
                        and stmts[-1].value is not None
                        and not _has_top_level_return(stmts[:-1]))
            if not (_tail_return_only(body) and _tail_return_only(orelse)):
                raise NotImplementedError(
                    "dy2static: `return` under a converted `if` must be the "
                    "final statement of BOTH branches; early/partial return "
                    "from a tensor condition has no lax.cond form")
            return self._rewrite_returning_if(node, body, orelse)
        carried = sorted(
            (set(_assigned_names(body)) | set(_assigned_names(orelse)))
            - _GENERATED)
        tf, ff = _fresh("true_fn"), _fresh("false_fn")
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=c) for c in carried],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(c) for c in carried], ctx=ast.Load()))
        t_def = ast.FunctionDef(name=tf, args=args, body=body + [ret],
                                decorator_list=[], type_params=[])
        f_def = ast.FunctionDef(name=ff, args=args, body=list(orelse) + [ret],
                                decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_ifelse",
                               ctx=ast.Load()),
            args=[node.test, _name(tf), _name(ff),
                  ast.Tuple(elts=[_name(c) for c in carried],
                            ctx=ast.Load())],
            keywords=_undef_ok_kw(carried))
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(c, ast.Store()) for c in carried],
                               ctx=ast.Store())],
            value=call) if carried else ast.Expr(value=call)
        out = _undefined_default(carried) + [t_def, f_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def _rewrite_returning_if(self, node, body, orelse):
        """Both branches end in return: `return convert_ifelse(...)`."""
        tf, ff = _fresh("true_fn"), _fresh("false_fn")
        args = ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                             kw_defaults=[], defaults=[])
        t_def = ast.FunctionDef(name=tf, args=args, body=body,
                                decorator_list=[], type_params=[])
        f_def = ast.FunctionDef(name=ff, args=args, body=orelse,
                                decorator_list=[], type_params=[])
        ret = ast.Return(value=ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_ifelse",
                               ctx=ast.Load()),
            args=[node.test, _name(tf), _name(ff),
                  ast.Tuple(elts=[], ctx=ast.Load())],
            keywords=[]))
        out = [t_def, f_def, ret]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- while --------------------------------------------------------------

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            raise NotImplementedError("dy2static: while/else not supported")
        if _owned_break_continue(node.body):
            # Leave untransformed: valid for Python-valued conditions;
            # tensor conditions will fail in jax with a clear tracer error.
            # (break/continue are normally consumed by the rewriter pass —
            # this only triggers for unconverted constructs.)
            return node
        if _has_top_level_return(node.body):
            raise NotImplementedError(
                "dy2static: `return` inside a converted `while` body")
        # Carried state = names the body assigns. Loop-invariant reads (in
        # the condition or body) resolve through the closure instead.
        carried = sorted(set(_assigned_names(node.body)) - _GENERATED)
        cf, bf = _fresh("cond_fn"), _fresh("body_fn")
        args = ast.arguments(posonlyargs=[],
                             args=[ast.arg(arg=c) for c in carried],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        c_def = ast.FunctionDef(name=cf, args=args,
                                body=[ast.Return(value=node.test)],
                                decorator_list=[], type_params=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(c) for c in carried], ctx=ast.Load()))
        b_def = ast.FunctionDef(name=bf, args=args, body=node.body + [ret],
                                decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_while",
                               ctx=ast.Load()),
            args=[_name(cf), _name(bf),
                  ast.Tuple(elts=[_name(c) for c in carried],
                            ctx=ast.Load())],
            keywords=_undef_ok_kw(carried, node.body, [node.test]))
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(c, ast.Store()) for c in carried],
                               ctx=ast.Store())],
            value=call) if carried else ast.Expr(value=call)
        out = _undefined_default(carried) + [c_def, b_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- for i in range(...) ------------------------------------------------

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.orelse
                    and isinstance(node.target, ast.Name)
                    and not _owned_break_continue(node.body))
        if not is_range:
            return node  # plain Python iteration (lists, enumerate, ...)
        if _has_top_level_return(node.body):
            raise NotImplementedError(
                "dy2static: `return` inside a converted `for` body")
        rargs = node.iter.args
        start = rargs[0] if len(rargs) > 1 else ast.Constant(value=0)
        stop = rargs[1] if len(rargs) > 1 else rargs[0]
        step = rargs[2] if len(rargs) > 2 else ast.Constant(value=1)
        carried = sorted(set(_assigned_names(node.body))
                         - {node.target.id} - _GENERATED)
        bf = _fresh("for_body")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=node.target.id)] +
                 [ast.arg(arg=c) for c in carried],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(c) for c in carried], ctx=ast.Load()))
        b_def = ast.FunctionDef(name=bf, args=args, body=node.body + [ret],
                                decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Attribute(value=_name("__jst"),
                               attr="convert_for_range", ctx=ast.Load()),
            args=[start, stop, step, _name(bf),
                  ast.Tuple(elts=[_name(c) for c in carried],
                            ctx=ast.Load())],
            keywords=_undef_ok_kw(carried, node.body, rargs))
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(c, ast.Store()) for c in carried],
                               ctx=ast.Store())],
            value=call) if carried else ast.Expr(value=call)
        out = _undefined_default(carried) + [b_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- boolean operators --------------------------------------------------

    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        conv = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        expr = node.values[-1]
        for lhs in reversed(node.values[:-1]):
            rhs_fn = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=expr)
            expr = ast.Call(
                func=ast.Attribute(value=_name("__jst"), attr=conv,
                                   ctx=ast.Load()),
                args=[lhs, rhs_fn], keywords=[])
        ast.copy_location(expr, node)
        ast.fix_missing_locations(expr)
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Not):
            return node
        out = ast.Call(
            func=ast.Attribute(value=_name("__jst"),
                               attr="convert_logical_not", ctx=ast.Load()),
            args=[node.operand], keywords=[])
        ast.copy_location(out, node)
        ast.fix_missing_locations(out)
        return out

    # -- assert / casts -----------------------------------------------------

    def visit_Assert(self, node: ast.Assert):
        self.generic_visit(node)
        out = ast.Expr(value=ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_assert",
                               ctx=ast.Load()),
            args=[node.test] + ([node.msg] if node.msg else []),
            keywords=[]))
        ast.copy_location(out, node)
        ast.fix_missing_locations(out)
        return out

    _CAST_FNS = {"int": "convert_int", "float": "convert_float",
                 "bool": "convert_bool", "len": "convert_len"}

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name)
                and node.func.id in self._CAST_FNS
                and len(node.args) == 1 and not node.keywords
                and not isinstance(node.args[0], ast.Starred)):
            out = ast.Call(
                func=ast.Attribute(value=_name("__jst"),
                                   attr=self._CAST_FNS[node.func.id],
                                   ctx=ast.Load()),
                args=node.args, keywords=[])
            ast.copy_location(out, node)
            ast.fix_missing_locations(out)
            return out
        return node


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def _report_unconverted(fn, reason: str) -> None:
    """Under FLAGS_static_analysis, a silent conversion fallback becomes a
    visible diagnostic: the function will trace as-is, so a tensor `if`
    inside it fails with a raw tracer error instead of lax.cond."""
    from ..analysis import jaxpr_lint
    if jaxpr_lint.analysis_mode() == "off":
        return
    name = getattr(fn, "__qualname__", repr(fn))
    jaxpr_lint.emit([jaxpr_lint.Diagnostic(
        rule="Y001", name="dy2static-unconverted",
        severity=jaxpr_lint.WARNING,
        message=f"dy2static could not convert {name}: {reason}; "
                "data-dependent Python control flow inside it will not "
                "lower to lax.cond/while_loop",
        hint="define the function in a plain module/def so its source is "
             "importable, or restructure with jnp.where")],
        where="dy2static")


def convert_to_static(fn: Callable) -> Callable:
    """AST-convert a Python function's control flow for tracing (ref
    program_translator.py:313 StaticFunction conversion step).

    Returns a new function with identical signature whose ``if``/``while``/
    ``for range``/boolean ops dispatch through lax control flow when traced.
    Falls back to the original function when source is unavailable
    (builtins, lambdas, C extensions)."""
    if getattr(fn, "__jst_converted__", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, IndentationError, SyntaxError) as e:
        _report_unconverted(fn, f"source unavailable ({type(e).__name__})")
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        _report_unconverted(fn, "not a plain function definition")
        return fn
    fdef.decorator_list = []  # run undecorated; to_static re-wraps
    # pass order matters: early returns become flags first, then
    # break/continue become flags, then the flag-based control flow is
    # lowered to lax (ref: transform_ordering in program_translator.py)
    _ReturnRewriter().rewrite(fdef)
    _BreakContinueRewriter().visit(tree)
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    import sys
    this = sys.modules[__name__]
    glb = dict(fn.__globals__)
    glb["__jst"] = this
    # Rebind the original closure cells, if any.
    if fn.__closure__:
        freevars = fn.__code__.co_freevars
        for name, cell in zip(freevars, fn.__closure__):
            try:
                glb.setdefault(name, cell.cell_contents)
            except ValueError:
                pass
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[fdef.name]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__jst_converted__ = True
    return new_fn
