"""dy2static: AST conversion of data-dependent Python control flow.

The reference rewrites Python ``if``/``while``/``for`` over tensors into
static-graph control-flow ops by AST transformation
(``python/paddle/jit/dy2static/ifelse_transformer.py:1``,
``loop_transformer.py``, driven by ``program_translator.py:313``). Pure
tracing — the default JAX conversion — cannot handle a branch on a traced
value. This module is the TPU-native form of those transformers: the same
source rewrite, but the hoisted branch/loop functions dispatch to
``lax.cond`` / ``lax.while_loop`` / ``lax.fori_loop`` when the condition is
a tracer, and run plain Python otherwise (so converted functions behave
identically outside jit).

What converts:

- ``if``/``elif``/``else`` over tensor conditions → ``lax.cond`` with the
  branch-assigned variables as carried operands (write-set analysis, like
  the reference's ``NameVisitor``);
- ``while`` over tensor conditions → ``lax.while_loop``;
- ``for i in range(...)`` with traced bounds → ``lax.fori_loop``;
- ``and`` / ``or`` / ``not`` over tensors → ``jnp.logical_*`` (both sides
  evaluate — short-circuit semantics are Python-only).

Out of scope (loud errors, matching the reference's supported envelope):
``break``/``continue`` under a tensor condition, ``return`` from only one
branch of a tensor ``if``.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, List, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["convert_to_static", "Undefined", "UNDEFINED",
           "convert_ifelse", "convert_while", "convert_for_range",
           "convert_logical_and", "convert_logical_or", "convert_logical_not"]


class Undefined:
    """Sentinel for a name assigned in only one branch (ref dy2static
    UndefinedVar). Using it under a tensor condition is an error; under a
    Python condition it simply never escapes the taken branch."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<dy2static undefined>"


UNDEFINED = Undefined()

# Undefined is an *empty static pytree* (the reference's UndefinedVar): it
# flattens to zero leaves, so an unread UNDEFINED operand costs lax.cond
# nothing, and a branch that fails to assign a name returns UNDEFINED whose
# treedef mismatches the other branch's array — a structural error exactly
# when the program is genuinely ill-formed.
jax.tree_util.register_pytree_node(
    Undefined, lambda u: ((), None), lambda aux, children: UNDEFINED)


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# Runtime converters (the generated code calls these)
# ---------------------------------------------------------------------------

def convert_ifelse(cond, true_fn, false_fn, operands: tuple):
    """Dispatch an ``if``: lax.cond for traced conditions, Python otherwise."""
    if _is_traced(cond) or any(_is_traced(o) for o in operands):
        if not _is_traced(cond):
            # Concrete cond with traced operands: still take one branch
            # eagerly — matches Python semantics and avoids tracing both.
            return true_fn(*operands) if cond else false_fn(*operands)
        try:
            return lax.cond(cond, true_fn, false_fn, *operands)
        except TypeError as e:
            if "Undefined" in str(e) or "pytree" in str(e) or \
                    "structure" in str(e):
                raise ValueError(
                    "dy2static: a variable assigned in only one branch of a "
                    "tensor `if` is used afterwards; initialize it before "
                    "the branch so both lax.cond branches return the same "
                    "structure") from e
            raise
    return true_fn(*operands) if cond else false_fn(*operands)


def convert_while(cond_fn, body_fn, operands: tuple):
    """Dispatch a ``while``: lax.while_loop when the condition traces."""
    probe = cond_fn(*operands)
    if _is_traced(probe) or any(_is_traced(o) for o in operands):
        for o in operands:
            if o is UNDEFINED:
                raise ValueError(
                    "dy2static: initialize every loop variable before a "
                    "tensor `while` loop (a name assigned in the loop body "
                    "has no value on entry)")
        return lax.while_loop(lambda c: cond_fn(*c), lambda c: body_fn(*c),
                              operands)
    while probe:
        operands = body_fn(*operands)
        probe = cond_fn(*operands)
    return operands


def convert_for_range(start, stop, step, body_fn, operands: tuple):
    """Dispatch ``for i in range(...)``: lax.fori_loop (step 1, traced
    bounds) / lax.while_loop (general step) / Python range otherwise."""
    traced = any(_is_traced(x) for x in (start, stop, step)) or \
        any(_is_traced(o) for o in operands)
    if traced:
        for o in operands:
            if o is UNDEFINED:
                raise ValueError(
                    "dy2static: initialize every loop variable before a "
                    "traced `for` loop")
        if isinstance(step, int) and step == 1:
            return lax.fori_loop(start, stop,
                                 lambda i, c: body_fn(i, *c), operands)
        i0 = jnp.asarray(start)

        def cond(c):
            i = c[0]
            return jnp.where(step > 0, i < stop, i > stop)

        def body(c):
            i, rest = c[0], c[1:]
            return (i + step,) + tuple(body_fn(i, *rest))

        return lax.while_loop(cond, body, (i0,) + tuple(operands))[1:]
    for i in range(start, stop, step):
        operands = tuple(body_fn(i, *operands))
    return operands


def convert_logical_and(lhs, rhs_fn):
    if _is_traced(lhs) or isinstance(lhs, jax.Array):
        return jnp.logical_and(lhs, rhs_fn())
    return lhs and rhs_fn()


def convert_logical_or(lhs, rhs_fn):
    if _is_traced(lhs) or isinstance(lhs, jax.Array):
        return jnp.logical_or(lhs, rhs_fn())
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_traced(x) or isinstance(x, jax.Array):
        return jnp.logical_not(x)
    return not x


# ---------------------------------------------------------------------------
# Static analysis helpers (ref dy2static NameVisitor)
# ---------------------------------------------------------------------------

def _assigned_names(nodes: Sequence[ast.stmt]) -> List[str]:
    """Names bound by assignment anywhere in `nodes` (order-stable)."""
    out: List[str] = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    node.id not in out:
                out.append(node.id)

        def visit_FunctionDef(self, node):  # don't descend into nested defs
            if node.name not in out:
                out.append(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    for n in nodes:
        V().visit(n)
    return out


def _read_names(nodes: Sequence[ast.AST]) -> Set[str]:
    out: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                out.add(node.id)

    for n in nodes:
        V().visit(n)
    return out


def _contains(nodes: Sequence[ast.stmt], kinds) -> bool:
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, kinds):
                return True
    return False


def _has_top_level_return(nodes: Sequence[ast.stmt]) -> bool:
    """Return statements excluding those inside nested function defs."""
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(sub, ast.Return):
                return True
    return False


_CTR = [0]


def _fresh(prefix: str) -> str:
    _CTR[0] += 1
    return f"__jst_{prefix}_{_CTR[0]}"


class _GeneratedNames:
    """`some_set - _GENERATED` filters out generated helper names, which
    must never join a carried-variable set (they are functions)."""

    def __rsub__(self, other):
        return {n for n in other if not n.startswith("__jst_")}


_GENERATED = _GeneratedNames()


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _undefined_default(names: Sequence[str]) -> List[ast.stmt]:
    """`name = __jst.UNDEFINED if '<name>' not in dir() else name` — cheaper:
    we emit  try/except NameError guards so names missing on entry carry the
    sentinel."""
    stmts = []
    for nm in names:
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[_name(nm, ast.Store())],
                             value=_name(nm))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_name("NameError"),
                                     _name("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[_name(nm, ast.Store())],
                    value=ast.Attribute(value=_name("__jst"),
                                        attr="UNDEFINED", ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return stmts


class _ControlFlowTransformer(ast.NodeTransformer):
    """Bottom-up rewrite of if/while/for-range/boolops into __jst calls."""

    # -- if / elif / else ---------------------------------------------------

    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse or [ast.Pass()]
        t_ret = _has_top_level_return(body)
        f_ret = _has_top_level_return(orelse)
        if t_ret or f_ret:
            # Only the simple total form converts: each branch is exactly
            # one final `return <expr>` (possibly after other statements,
            # none of which return).
            def _tail_return_only(stmts):
                return (stmts and isinstance(stmts[-1], ast.Return)
                        and stmts[-1].value is not None
                        and not _has_top_level_return(stmts[:-1]))
            if not (_tail_return_only(body) and _tail_return_only(orelse)):
                raise NotImplementedError(
                    "dy2static: `return` under a converted `if` must be the "
                    "final statement of BOTH branches; early/partial return "
                    "from a tensor condition has no lax.cond form")
            return self._rewrite_returning_if(node, body, orelse)
        carried = sorted(
            (set(_assigned_names(body)) | set(_assigned_names(orelse)))
            - _GENERATED)
        tf, ff = _fresh("true_fn"), _fresh("false_fn")
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=c) for c in carried],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(c) for c in carried], ctx=ast.Load()))
        t_def = ast.FunctionDef(name=tf, args=args, body=body + [ret],
                                decorator_list=[], type_params=[])
        f_def = ast.FunctionDef(name=ff, args=args, body=list(orelse) + [ret],
                                decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_ifelse",
                               ctx=ast.Load()),
            args=[node.test, _name(tf), _name(ff),
                  ast.Tuple(elts=[_name(c) for c in carried],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(c, ast.Store()) for c in carried],
                               ctx=ast.Store())],
            value=call) if carried else ast.Expr(value=call)
        out = _undefined_default(carried) + [t_def, f_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def _rewrite_returning_if(self, node, body, orelse):
        """Both branches end in return: `return convert_ifelse(...)`."""
        tf, ff = _fresh("true_fn"), _fresh("false_fn")
        args = ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                             kw_defaults=[], defaults=[])
        t_def = ast.FunctionDef(name=tf, args=args, body=body,
                                decorator_list=[], type_params=[])
        f_def = ast.FunctionDef(name=ff, args=args, body=orelse,
                                decorator_list=[], type_params=[])
        ret = ast.Return(value=ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_ifelse",
                               ctx=ast.Load()),
            args=[node.test, _name(tf), _name(ff),
                  ast.Tuple(elts=[], ctx=ast.Load())],
            keywords=[]))
        out = [t_def, f_def, ret]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- while --------------------------------------------------------------

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            raise NotImplementedError("dy2static: while/else not supported")
        if _contains(node.body, (ast.Break, ast.Continue)):
            # Leave untransformed: valid for Python-valued conditions;
            # tensor conditions will fail in jax with a clear tracer error.
            return node
        if _has_top_level_return(node.body):
            raise NotImplementedError(
                "dy2static: `return` inside a converted `while` body")
        # Carried state = names the body assigns. Loop-invariant reads (in
        # the condition or body) resolve through the closure instead.
        carried = sorted(set(_assigned_names(node.body)) - _GENERATED)
        cf, bf = _fresh("cond_fn"), _fresh("body_fn")
        args = ast.arguments(posonlyargs=[],
                             args=[ast.arg(arg=c) for c in carried],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        c_def = ast.FunctionDef(name=cf, args=args,
                                body=[ast.Return(value=node.test)],
                                decorator_list=[], type_params=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(c) for c in carried], ctx=ast.Load()))
        b_def = ast.FunctionDef(name=bf, args=args, body=node.body + [ret],
                                decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Attribute(value=_name("__jst"), attr="convert_while",
                               ctx=ast.Load()),
            args=[_name(cf), _name(bf),
                  ast.Tuple(elts=[_name(c) for c in carried],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(c, ast.Store()) for c in carried],
                               ctx=ast.Store())],
            value=call) if carried else ast.Expr(value=call)
        out = _undefined_default(carried) + [c_def, b_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- for i in range(...) ------------------------------------------------

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.orelse
                    and isinstance(node.target, ast.Name)
                    and not _contains(node.body, (ast.Break, ast.Continue)))
        if not is_range:
            return node  # plain Python iteration (lists, enumerate, ...)
        if _has_top_level_return(node.body):
            raise NotImplementedError(
                "dy2static: `return` inside a converted `for` body")
        rargs = node.iter.args
        start = rargs[0] if len(rargs) > 1 else ast.Constant(value=0)
        stop = rargs[1] if len(rargs) > 1 else rargs[0]
        step = rargs[2] if len(rargs) > 2 else ast.Constant(value=1)
        carried = sorted(set(_assigned_names(node.body))
                         - {node.target.id} - _GENERATED)
        bf = _fresh("for_body")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=node.target.id)] +
                 [ast.arg(arg=c) for c in carried],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(c) for c in carried], ctx=ast.Load()))
        b_def = ast.FunctionDef(name=bf, args=args, body=node.body + [ret],
                                decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Attribute(value=_name("__jst"),
                               attr="convert_for_range", ctx=ast.Load()),
            args=[start, stop, step, _name(bf),
                  ast.Tuple(elts=[_name(c) for c in carried],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(c, ast.Store()) for c in carried],
                               ctx=ast.Store())],
            value=call) if carried else ast.Expr(value=call)
        out = _undefined_default(carried) + [b_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- boolean operators --------------------------------------------------

    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        conv = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        expr = node.values[-1]
        for lhs in reversed(node.values[:-1]):
            rhs_fn = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=expr)
            expr = ast.Call(
                func=ast.Attribute(value=_name("__jst"), attr=conv,
                                   ctx=ast.Load()),
                args=[lhs, rhs_fn], keywords=[])
        ast.copy_location(expr, node)
        ast.fix_missing_locations(expr)
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Not):
            return node
        out = ast.Call(
            func=ast.Attribute(value=_name("__jst"),
                               attr="convert_logical_not", ctx=ast.Load()),
            args=[node.operand], keywords=[])
        ast.copy_location(out, node)
        ast.fix_missing_locations(out)
        return out


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def convert_to_static(fn: Callable) -> Callable:
    """AST-convert a Python function's control flow for tracing (ref
    program_translator.py:313 StaticFunction conversion step).

    Returns a new function with identical signature whose ``if``/``while``/
    ``for range``/boolean ops dispatch through lax control flow when traced.
    Falls back to the original function when source is unavailable
    (builtins, lambdas, C extensions)."""
    if getattr(fn, "__jst_converted__", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, IndentationError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # run undecorated; to_static re-wraps
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    import sys
    this = sys.modules[__name__]
    glb = dict(fn.__globals__)
    glb["__jst"] = this
    # Rebind the original closure cells, if any.
    if fn.__closure__:
        freevars = fn.__code__.co_freevars
        for name, cell in zip(freevars, fn.__closure__):
            try:
                glb.setdefault(name, cell.cell_contents)
            except ValueError:
                pass
    loc: dict = {}
    exec(code, glb, loc)
    new_fn = loc[fdef.name]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__jst_converted__ = True
    return new_fn
