"""paddle.jit parity: to_static / save / load.

The reference converts imperative Python to a static ProgramDesc by AST
rewriting (``python/paddle/jit/api.py:233`` @to_static, dy2static
transformers, ``StaticFunction`` at ``program_translator.py:313``). On
JAX none of that is needed: tracing a jittable forward IS the conversion.

- :func:`to_static` wraps a function or Layer into a :class:`StaticFunction`
  that jit-compiles per input signature (shape/dtype cache, the analog of
  the reference's program cache keyed like ``_ExecutorCache``).
- :func:`save`/:func:`load` AOT-export a traced function via jax.export
  (StableHLO) — the inference deployment format (the reference's
  ``jit.save`` → TranslatedLayer path).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..framework.functional import functional_call, get_params, get_buffers

__all__ = ["to_static", "StaticFunction", "save", "load", "TranslatedLayer",
           "not_to_static", "ignore_module", "dy2static",
           "enable_to_static", "set_verbosity", "set_code_level"]


def _abstractify(tree):
    return jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), jnp.asarray(a).dtype)
        if hasattr(a, "shape") or isinstance(a, (int, float)) else a, tree)


def _maybe_lint(fn, args, kwargs, where: str) -> None:
    """FLAGS_static_analysis hook: lint the traced program for this input
    signature before compiling (warn prints, error raises GraphLintError).
    Trace failures here are ignored — jit itself will produce the real
    error with full context."""
    from ..analysis import jaxpr_lint
    if jaxpr_lint.analysis_mode() == "off":
        return
    try:
        diags = jaxpr_lint.lint_fn(fn, *args, where=where, **kwargs)
    except Exception:
        return
    jaxpr_lint.emit(diags, where=where)


class StaticFunction:
    """Compiled-function cache front (ref StaticFunction/partial_program)."""

    def __init__(self, fn_or_layer, input_spec=None, build_strategy=None,
                 full_graph: bool = True):
        self._target = fn_or_layer
        self._input_spec = input_spec
        self._is_layer = isinstance(fn_or_layer, Layer)
        self._cache: Dict[Any, Callable] = {}
        self._raw: Dict[Any, Callable] = {}
        self._linted: set = set()

    @property
    def code_cache_size(self) -> int:
        return len(self._cache)

    def _compiled_for(self, args, kwargs):
        key = (pickle.dumps(_abstractify(args)), pickle.dumps(_abstractify(kwargs)))
        fn = self._cache.get(key)
        if fn is None:
            if self._is_layer:
                layer = self._target

                def pure(params, buffers, *a, **k):
                    out, new_buf = functional_call(layer, params, *a,
                                                   buffers=buffers,
                                                   mutable=True, **k)
                    return out, new_buf

                fn = jax.jit(pure)
                self._raw[key] = pure
            else:
                # dy2static: AST-convert data-dependent Python control flow
                # into lax.cond/while_loop (ref dy2static transformers) so
                # tracing doesn't choke on `if tensor:`.
                from .dy2static import convert_to_static
                converted = convert_to_static(self._target)
                fn = jax.jit(converted)
                self._raw[key] = converted
            self._cache[key] = fn
        return key, fn

    def _lint_signature(self, key, args, kwargs):
        """FLAGS_static_analysis: lint each input signature once (flag-off
        calls don't consume the once, so enabling the flag later works)."""
        from ..analysis import jaxpr_lint
        if key in self._linted or jaxpr_lint.analysis_mode() == "off":
            return
        self._linted.add(key)
        name = getattr(self._target, "__name__",
                       type(self._target).__name__)
        _maybe_lint(self._raw[key], args, kwargs, where=f"to_static:{name}")

    def __call__(self, *args, **kwargs):
        key, fn = self._compiled_for(args, kwargs)
        if self._is_layer:
            layer = self._target
            params = get_params(layer)
            buffers = get_buffers(layer)
            self._lint_signature(key, (params, buffers) + args, kwargs)
            out, new_buf = fn(params, buffers, *args, **kwargs)
            from ..framework.functional import set_buffers
            if new_buf:
                set_buffers(layer, new_buf)
            return out
        self._lint_signature(key, args, kwargs)
        return fn(*args, **kwargs)

    # paddle parity: concrete_program etc. are not meaningful; expose the
    # lowered StableHLO for inspection instead.
    def lowered(self, *args, **kwargs):
        _, fn = self._compiled_for(args, kwargs)
        if self._is_layer:
            params = get_params(self._target)
            buffers = get_buffers(self._target)
            return fn.lower(params, buffers, *args, **kwargs)
        return fn.lower(*args, **kwargs)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static parity decorator."""

    def decorate(fn):
        if not _to_static_enabled:
            return fn  # jit.enable_to_static(False): run eagerly
        return StaticFunction(fn, input_spec=input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    return fn


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# AOT export (inference format)
# ---------------------------------------------------------------------------

def _example_avals(input_spec):
    """input_spec entries -> ShapeDtypeStructs. A dim may be an int, None
    (a fresh symbolic dim), or a string name (symbolic, shared across any
    dims/specs using the same name) — symbolic dims export a
    shape-polymorphic StableHLO the Predictor can call at any size (it
    pads them to registered buckets to bound the compile count). All
    symbolic dims are created in ONE scope so shared names unify."""
    from jax import export as jax_export

    resolved = []  # (dims with str placeholders, dtype)
    names: list = []
    auto = 0
    for spec in input_spec:
        if hasattr(spec, "shape") and hasattr(spec, "dtype"):
            shape, dtype = tuple(spec.shape), spec.dtype
        else:
            shape, dtype = spec
            shape, dtype = tuple(shape), jnp.dtype(dtype)
        dims = []
        for d in shape:
            if d is None:
                d = f"_dyn{auto}"
                auto += 1
            if isinstance(d, str):
                if d not in names:
                    names.append(d)
                dims.append(d)
            else:
                dims.append(int(d))
        resolved.append((dims, dtype))
    if not names:
        return [jax.ShapeDtypeStruct(tuple(dims), dtype)
                for dims, dtype in resolved]
    by_name = dict(zip(names,
                       jax_export.symbolic_shape(", ".join(names))))
    return [jax.ShapeDtypeStruct(
        tuple(by_name[d] if isinstance(d, str) else d for d in dims),
        dtype) for dims, dtype in resolved]


def save(layer, path: str, input_spec=None, **configs) -> None:
    """Serialize a Layer for inference: params (pickle) + exported StableHLO.

    input_spec: list of (shape, dtype) tuples or example arrays for
    tracing; a shape dim of None (or a shared string name) exports that
    dim shape-polymorphic (see :func:`_example_avals`).
    """
    from jax import export as jax_export

    if input_spec is None:
        raise ValueError("jit.save requires input_spec (shapes can't be guessed)")
    example = _example_avals(input_spec)

    params = get_params(layer)
    buffers = get_buffers(layer)

    def infer_fn(params, buffers, *xs):
        layer.eval()
        return functional_call(layer, params, *xs, buffers=buffers)

    exported = jax_export.export(jax.jit(infer_fn))(
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        *example)

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    import numpy as np
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({
            "params": {k: np.asarray(v) for k, v in params.items()},
            "buffers": {k: np.asarray(v) for k, v in buffers.items()},
        }, f, protocol=4)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())


class TranslatedLayer:
    """Loaded inference function (ref: translated_layer.py)."""

    def __init__(self, exported, params, buffers):
        self._exported = exported
        self._params = params
        self._buffers = buffers

    def __call__(self, *args):
        return self._exported.call(self._params, self._buffers, *args)

    def eval(self):
        return self


def load(path: str) -> TranslatedLayer:
    from jax import export as jax_export
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    params = {k: jnp.asarray(v) for k, v in state["params"].items()}
    buffers = {k: jnp.asarray(v) for k, v in state["buffers"].items()}
    return TranslatedLayer(exported, params, buffers)


_to_static_enabled = True
_code_level = 0


def enable_to_static(flag: bool = True):
    """ref jit.enable_to_static: global switch — when off, to_static
    returns the original callable (eager)."""
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """ref dy2static set_verbosity — recorded; conversion logging hook."""
    global _code_level
    _code_level = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    set_verbosity(level, also_to_stdout)
