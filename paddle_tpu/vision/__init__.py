from . import models  # noqa: F401
from . import datasets  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401


# -- image backend (ref vision/image.py) -----------------------------------
_image_backend = "pil"


def set_image_backend(backend: str):
    """ref vision.set_image_backend: 'pil' | 'cv2'. Recorded and used by
    image_load; cv2 is absent in this image, so requesting it raises at
    load time, matching the reference's lazy failure."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    """ref vision.image_load: file -> PIL Image (or cv2 ndarray)."""
    b = backend or _image_backend
    if b == "cv2":
        import cv2  # raises if absent, like the reference
        return cv2.imread(path)
    from PIL import Image
    return Image.open(path)
