"""Vision datasets (ref: python/paddle/vision/datasets/). Network download is
unavailable in this environment, so MNIST supports a synthetic mode used by
tests/benchmarks; with a local `image_path`/`label_path` it reads the standard
IDX files."""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST"]


class MNIST(Dataset):
    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = False,
                 backend: str = "numpy", synthetic_size: Optional[int] = None):
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_idx_images(image_path)
            self.labels = self._read_idx_labels(label_path)
        else:
            # Synthetic fallback: deterministic pseudo-MNIST. Class
            # prototypes are shared across train/test (fixed seed) so
            # generalization is measurable; noise/labels differ per split.
            n = synthetic_size or (6000 if mode == "train" else 1000)
            base = np.random.default_rng(12345).standard_normal(
                (10, 28, 28)).astype(np.float32)
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.labels = rng.integers(0, 10, size=(n,)).astype(np.int64)
            noise = 0.3 * rng.standard_normal((n, 28, 28)).astype(np.float32)
            self.images = base[self.labels] + noise

    @staticmethod
    def _read_idx_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return (data.reshape(n, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_idx_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx][None, :, :]  # CHW
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


__all__ += ["Cifar10", "Cifar100", "Flowers", "DatasetFolder", "ImageFolder"]


class Cifar10(Dataset):
    """CIFAR-10 (ref datasets/cifar.py). With `data_file` pointing at the
    standard python-pickle tarball (or extracted batch files) it reads real
    data; otherwise a deterministic synthetic set (class prototypes + noise,
    split-consistent like MNIST above)."""

    _n_classes = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = False,
                 backend: str = "numpy", synthetic_size: Optional[int] = None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            self.images, self.labels = self._load_real(data_file, mode)
        else:
            n = synthetic_size or (5000 if mode == "train" else 1000)
            base = np.random.default_rng(54321).standard_normal(
                (self._n_classes, 3, 32, 32)).astype(np.float32)
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.labels = rng.integers(0, self._n_classes,
                                       size=(n,)).astype(np.int64)
            noise = 0.3 * rng.standard_normal((n, 3, 32, 32)) \
                .astype(np.float32)
            self.images = base[self.labels] + noise

    def _load_real(self, data_file, mode):
        import pickle
        import tarfile
        label_key = b"labels" if self._n_classes == 10 else b"fine_labels"
        imgs, labels = [], []

        def want(name):
            if self._n_classes == 10:
                return ("data_batch" in name) if mode == "train" \
                    else ("test_batch" in name)
            return name.endswith("train" if mode == "train" else "test")

        if tarfile.is_tarfile(data_file):
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    if m.isfile() and want(m.name):
                        d = pickle.load(tf.extractfile(m), encoding="bytes")
                        imgs.append(d[b"data"])
                        labels.extend(d[label_key])
        else:
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            imgs.append(d[b"data"])
            labels.extend(d[label_key])
        images = np.concatenate(imgs).reshape(-1, 3, 32, 32) \
            .astype(np.float32) / 255.0
        return images, np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    _n_classes = 100


class Flowers(Cifar10):
    """Flowers102-style dataset; synthetic fallback (ref datasets/flowers.py
    — real download is unavailable in this environment)."""

    _n_classes = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode: str = "train", transform=None, download: bool = False,
                 backend: str = "numpy", synthetic_size: Optional[int] = None):
        if data_file or label_file or setid_file:
            raise NotImplementedError(
                "Flowers: reading the real .mat files is not supported in "
                "this build (no scipy.io loader wired); only the synthetic "
                "mode is available — do not pass data/label/setid files")
        super().__init__(data_file=None, mode=mode, transform=transform,
                         synthetic_size=synthetic_size or
                         (1020 if mode == "train" else 102))


def _default_loader(path: str):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        import PIL.Image
        with PIL.Image.open(path) as img:
            return np.asarray(img.convert("RGB"))
    except ImportError as e:
        raise RuntimeError(
            f"cannot load {path}: PIL unavailable and not a .npy file") from e


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """class-per-subdirectory dataset (ref datasets/folder.py):
    root/class_x/xxx.ext -> (sample, class_index)."""

    def __init__(self, root: str, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = tuple(extensions) if extensions else IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        fname.lower().endswith(extensions)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (unlabeled) image folder (ref datasets/folder.py ImageFolder):
    returns [sample] per item."""

    def __init__(self, root: str, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = tuple(extensions) if extensions else IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(extensions)
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """ref vision/datasets/voc2012.py: segmentation pairs (image, label
    mask). No network in this environment — deterministic synthetic scenes
    (colored rectangles with matching class masks), same API."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = True,
                 backend: str = "numpy", synthetic_size: Optional[int] = None):
        self.mode = mode
        self.transform = transform
        n = synthetic_size or (100 if mode == "train" else 20)
        rng = np.random.default_rng(0 if mode == "train" else 1)
        self._images = []
        self._labels = []
        for _ in range(n):
            img = rng.integers(0, 64, (64, 64, 3)).astype(np.uint8)
            mask = np.zeros((64, 64), np.uint8)
            for _ in range(int(rng.integers(1, 4))):
                cls = int(rng.integers(1, 21))
                y0, x0 = rng.integers(0, 40, 2)
                hh, ww = rng.integers(8, 24, 2)
                img[y0:y0 + hh, x0:x0 + ww] = (cls * 12) % 255
                mask[y0:y0 + hh, x0:x0 + ww] = cls
            self._images.append(img)
            self._labels.append(mask)

    def __getitem__(self, idx):
        img, mask = self._images[idx], self._labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self._images)


__all__ += ["VOC2012"]
