"""Vision datasets (ref: python/paddle/vision/datasets/). Network download is
unavailable in this environment, so MNIST supports a synthetic mode used by
tests/benchmarks; with a local `image_path`/`label_path` it reads the standard
IDX files."""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST"]


class MNIST(Dataset):
    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = False,
                 backend: str = "numpy", synthetic_size: Optional[int] = None):
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_idx_images(image_path)
            self.labels = self._read_idx_labels(label_path)
        else:
            # Synthetic fallback: deterministic pseudo-MNIST. Class
            # prototypes are shared across train/test (fixed seed) so
            # generalization is measurable; noise/labels differ per split.
            n = synthetic_size or (6000 if mode == "train" else 1000)
            base = np.random.default_rng(12345).standard_normal(
                (10, 28, 28)).astype(np.float32)
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.labels = rng.integers(0, 10, size=(n,)).astype(np.int64)
            noise = 0.3 * rng.standard_normal((n, 28, 28)).astype(np.float32)
            self.images = base[self.labels] + noise

    @staticmethod
    def _read_idx_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return (data.reshape(n, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_idx_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx][None, :, :]  # CHW
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass
