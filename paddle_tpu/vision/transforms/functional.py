"""Functional image transforms (ref: python/paddle/vision/transforms/
functional.py + functional_cv2.py) — numpy host-side implementations; all
accept HWC or CHW numpy arrays (and PIL images where noted)."""

from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

__all__ = ["to_tensor", "normalize", "resize", "hflip", "vflip", "crop",
           "center_crop", "pad", "rotate", "to_grayscale",
           "adjust_brightness", "adjust_contrast", "adjust_saturation",
           "adjust_hue", "erase"]


def _is_chw(img: np.ndarray) -> bool:
    return img.ndim == 3 and img.shape[0] in (1, 3, 4)


def _as_hwc(img):
    img = np.asarray(img)
    if _is_chw(img):
        return img.transpose(1, 2, 0), True
    if img.ndim == 2:
        return img[..., None], False
    return img, False


def _restore(img, was_chw):
    if was_chw:
        return img.transpose(2, 0, 1)
    return img


def to_tensor(img, data_format: str = "CHW"):
    arr = np.asarray(img)
    # Scale by dtype, not by data-dependent range: a nearly-black uint8
    # image must not skip the /255 (ref functional.to_tensor semantics).
    if arr.dtype == np.uint8:
        img = arr.astype(np.float32) / 255.0
    else:
        img = arr.astype(np.float32)
    if img.ndim == 2:
        img = img[None] if data_format == "CHW" else img[..., None]
    elif data_format == "CHW" and img.shape[-1] in (1, 3, 4):
        img = img.transpose(2, 0, 1)
    return img


def normalize(img, mean, std, data_format: str = "CHW", to_rgb=False):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (img - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation: str = "bilinear"):
    """Bilinear/nearest resize in numpy (HWC/CHW/2D)."""
    arr, was_chw = _as_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        # paddle semantics: shorter edge -> size, keep aspect
        if h <= w:
            oh, ow = size, max(1, round(w * size / h))
        else:
            oh, ow = max(1, round(h * size / w)), size
    else:
        oh, ow = size
    if interpolation == "nearest":
        ys = np.clip((np.arange(oh) + 0.5) * h / oh, 0, h - 1).astype(int)
        xs = np.clip((np.arange(ow) + 0.5) * w / ow, 0, w - 1).astype(int)
        out = arr[ys][:, xs]
    else:  # bilinear
        ys = (np.arange(oh) + 0.5) * h / oh - 0.5
        xs = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0, 1)[:, None, None]
        wx = np.clip(xs - x0, 0, 1)[None, :, None]
        a = arr.astype(np.float32)
        out = ((a[y0][:, x0] * (1 - wy) * (1 - wx))
               + (a[y0][:, x1] * (1 - wy) * wx)
               + (a[y1][:, x0] * wy * (1 - wx))
               + (a[y1][:, x1] * wy * wx))
        if np.issubdtype(arr.dtype, np.integer):
            out = np.round(out).astype(arr.dtype)
        else:
            out = out.astype(arr.dtype)
    if np.asarray(img).ndim == 2:
        out = out[..., 0]
        return out
    return _restore(out, was_chw)


def hflip(img):
    arr = np.asarray(img)
    return arr[..., ::-1].copy() if _is_chw(arr) or arr.ndim == 2 \
        else arr[:, ::-1].copy()


def vflip(img):
    arr = np.asarray(img)
    if _is_chw(arr):
        return arr[:, ::-1].copy()
    return arr[::-1].copy()


def crop(img, top: int, left: int, height: int, width: int):
    arr = np.asarray(img)
    if _is_chw(arr):
        return arr[:, top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = np.asarray(img)
    h, w = arr.shape[1:3] if _is_chw(arr) else arr.shape[:2]
    th, tw = output_size
    return crop(arr, (h - th) // 2, (w - tw) // 2, th, tw)


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = np.asarray(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    chw = _is_chw(arr)
    widths = [(0, 0)] * arr.ndim
    if chw:
        widths[1] = (pt, pb)
        widths[2] = (pl, pr)
    else:
        widths[0] = (pt, pb)
        widths[1] = (pl, pr)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(arr, widths, mode=mode, **kw)


def rotate(img, angle: float, interpolation: str = "nearest",
           expand: bool = False, center=None, fill=0):
    """Rotate counter-clockwise by `angle` degrees (nearest sampling)."""
    arr, was_chw = _as_hwc(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else \
        (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        corners = np.array([[-cy, -cx], [-cy, w - 1 - cx],
                            [h - 1 - cy, -cx], [h - 1 - cy, w - 1 - cx]])
        ys = corners[:, 0] * cos - corners[:, 1] * sin
        xs = corners[:, 0] * sin + corners[:, 1] * cos
        oh = int(np.ceil(ys.max() - ys.min() + 1))
        ow = int(np.ceil(xs.max() - xs.min() + 1))
        ocy, ocx = (oh - 1) / 2, (ow - 1) / 2
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(oh, dtype=np.float64) - ocy,
                         np.arange(ow, dtype=np.float64) - ocx,
                         indexing="ij")
    # inverse mapping (sample source for each output pixel)
    sy = yy * cos + xx * sin + cy
    sx = -yy * sin + xx * cos + cx
    if interpolation == "bilinear":
        eps = 1e-6  # boundary pixels land exactly on h-1/w-1 up to fp error
        valid = (sy >= -eps) & (sy <= h - 1 + eps) \
            & (sx >= -eps) & (sx <= w - 1 + eps)
        sy = np.clip(sy, 0, h - 1)
        sx = np.clip(sx, 0, w - 1)
        y0 = np.floor(sy).astype(int)
        x0 = np.floor(sx).astype(int)
        wy = (sy - y0)[..., None]
        wx = (sx - x0)[..., None]

        def at(yi, xi):
            return arr[np.clip(yi, 0, h - 1),
                       np.clip(xi, 0, w - 1)].astype(np.float64)

        val = (at(y0, x0) * (1 - wy) * (1 - wx)
               + at(y0, x0 + 1) * (1 - wy) * wx
               + at(y0 + 1, x0) * wy * (1 - wx)
               + at(y0 + 1, x0 + 1) * wy * wx)
        out = np.full((oh, ow, arr.shape[2]), fill, dtype=arr.dtype)
        out[valid] = np.round(val[valid]).astype(arr.dtype) \
            if np.issubdtype(arr.dtype, np.integer) \
            else val[valid].astype(arr.dtype)
    else:  # nearest
        syi = np.round(sy).astype(int)
        sxi = np.round(sx).astype(int)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full((oh, ow, arr.shape[2]), fill, dtype=arr.dtype)
        out[valid] = arr[syi[valid], sxi[valid]]
    if np.asarray(img).ndim == 2:
        return out[..., 0]
    return _restore(out, was_chw)


def to_grayscale(img, num_output_channels: int = 1):
    arr, was_chw = _as_hwc(img)
    if arr.shape[2] >= 3:
        gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                + 0.114 * arr[..., 2])
    else:
        gray = arr[..., 0]
    gray = gray.astype(arr.dtype)[..., None]
    out = np.repeat(gray, num_output_channels, axis=2)
    return _restore(out, was_chw)


def _blend(a, b, factor):
    out = a.astype(np.float32) * factor + b.astype(np.float32) * (1 - factor)
    if np.issubdtype(np.asarray(a).dtype, np.integer):
        return np.clip(out, 0, 255).astype(np.asarray(a).dtype)
    return out.astype(np.asarray(a).dtype)


def adjust_brightness(img, brightness_factor: float):
    arr = np.asarray(img)
    return _blend(arr, np.zeros_like(arr), brightness_factor)


def adjust_contrast(img, contrast_factor: float):
    arr, was_chw = _as_hwc(img)
    mean = to_grayscale(arr).mean()
    out = _blend(arr, np.full_like(arr, mean), contrast_factor)
    return _restore(out, was_chw)


def adjust_saturation(img, saturation_factor: float):
    arr, was_chw = _as_hwc(img)
    gray = to_grayscale(arr, arr.shape[2])
    out = _blend(arr, gray, saturation_factor)
    return _restore(out, was_chw)


def adjust_hue(img, hue_factor: float):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via HSV roundtrip."""
    assert -0.5 <= hue_factor <= 0.5
    arr, was_chw = _as_hwc(img)
    a = arr.astype(np.float32)
    scale = 255.0 if arr.dtype == np.uint8 or a.max() > 1.0 else 1.0
    a = a[..., :3] / scale  # hue acts on RGB only; alpha re-attached below
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    maxc = a.max(-1)
    minc = a.min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0)
    dz = np.maximum(delta, 1e-12)
    hr = np.where((maxc == r), (g - b) / dz % 6, 0)
    hg = np.where((maxc == g) & (maxc != r), (b - r) / dz + 2, 0)
    hb = np.where((maxc == b) & (maxc != r) & (maxc != g),
                  (r - g) / dz + 4, 0)
    hue = (hr + hg + hb) / 6.0
    hue = (hue + hue_factor) % 1.0
    i = np.floor(hue * 6.0)
    f = hue * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = (i.astype(int) % 6)[..., None]  # broadcast over the channel axis
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = out * scale
    if arr.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    else:
        out = out.astype(arr.dtype)
    if arr.shape[2] > 3:  # preserve alpha
        out = np.concatenate([out, arr[..., 3:]], axis=2)
    return _restore(out, was_chw)


def erase(img, i: int, j: int, h: int, w: int, v, inplace: bool = False):
    arr = np.asarray(img) if inplace else np.asarray(img).copy()
    if _is_chw(arr):
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr


def _inverse_sample(arr, inv_map, interpolation, fill):
    """Sample arr at inverse-mapped coords (shared by affine/perspective)."""
    h, w = arr.shape[:2]
    oh, ow = h, w
    yy, xx = np.meshgrid(np.arange(oh, dtype=np.float64),
                         np.arange(ow, dtype=np.float64), indexing="ij")
    sy, sx = inv_map(yy, xx)
    syi = np.round(sy).astype(int)
    sxi = np.round(sx).astype(int)
    valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
    out = np.full((oh, ow, arr.shape[2]), fill, dtype=arr.dtype)
    out[valid] = arr[syi[valid], sxi[valid]]
    return out, valid


def affine(img, angle: float, translate, scale: float, shear,
           interpolation: str = "nearest", fill=0, center=None):
    """Affine transform (ref transforms/functional.py affine): rotate +
    translate + scale + shear about the image centre."""
    arr, was_chw = _as_hwc(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else \
        (center[1], center[0])
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    rad = np.deg2rad(angle)
    sx_r, sy_r = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward matrix: R @ Shear * scale
    a = np.cos(rad + sy_r) * scale
    b = -np.sin(rad + sy_r) * scale
    c = np.sin(rad + sx_r) * scale
    d = np.cos(rad + sx_r) * scale
    m = np.array([[d, -b], [-c, a]]) / (a * d - b * c)  # inverse

    def inv(yy, xx):
        ty, tx = translate[1], translate[0]
        ry = yy - cy - ty
        rx = xx - cx - tx
        sy = m[0, 0] * ry + m[0, 1] * rx + cy
        sxx = m[1, 0] * ry + m[1, 1] * rx + cx
        return sy, sxx

    out, _ = _inverse_sample(arr, inv, interpolation, fill)
    if np.asarray(img).ndim == 2:
        return out[..., 0]
    return _restore(out, was_chw)


def perspective(img, startpoints, endpoints, interpolation: str = "nearest",
                fill=0):
    """Perspective warp mapping startpoints -> endpoints (ref
    transforms/functional.py perspective): solve the 8-dof homography,
    inverse-sample."""
    arr, was_chw = _as_hwc(img)
    sp = np.asarray(startpoints, np.float64)   # [(x, y)] * 4
    ep = np.asarray(endpoints, np.float64)
    # homography H with ep = H @ sp; build from endpoint->startpoint for
    # inverse sampling
    A = []
    bvec = []
    for (xs, ys), (xe, ye) in zip(sp, ep):
        A.append([xe, ye, 1, 0, 0, 0, -xs * xe, -xs * ye])
        bvec.append(xs)
        A.append([0, 0, 0, xe, ye, 1, -ys * xe, -ys * ye])
        bvec.append(ys)
    coef = np.linalg.solve(np.asarray(A), np.asarray(bvec))
    hmat = np.append(coef, 1.0).reshape(3, 3)

    def inv(yy, xx):
        denom = hmat[2, 0] * xx + hmat[2, 1] * yy + hmat[2, 2]
        sx = (hmat[0, 0] * xx + hmat[0, 1] * yy + hmat[0, 2]) / denom
        sy = (hmat[1, 0] * xx + hmat[1, 1] * yy + hmat[1, 2]) / denom
        return sy, sx

    out, _ = _inverse_sample(arr, inv, interpolation, fill)
    if np.asarray(img).ndim == 2:
        return out[..., 0]
    return _restore(out, was_chw)
