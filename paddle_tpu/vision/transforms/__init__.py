"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy host-side
preprocessing; heavy augmentation pipelines belong in the input pipeline, not
on the TPU."""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "RandomHorizontalFlip",
           "RandomCrop", "CenterCrop", "Transpose"]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        from . import functional as _F
        return _F.normalize(img, self.mean, self.std, self.data_format)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        from . import functional as _F
        return _F.to_tensor(img, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        try:
            import PIL.Image
            if isinstance(img, PIL.Image.Image):
                return np.asarray(img.resize(self.size[::-1]))
        except ImportError:
            pass
        # nearest-neighbor numpy resize
        img = np.asarray(img)
        h, w = img.shape[-2:] if img.ndim == 3 and img.shape[0] in (1, 3, 4) \
            else img.shape[:2]
        oh, ow = self.size
        ys = (np.arange(oh) * h / oh).astype(int)
        xs = (np.arange(ow) * w / ow).astype(int)
        if img.ndim == 3 and img.shape[0] in (1, 3, 4):
            return img[:, ys][:, :, xs]
        return img[ys][:, xs]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob
        self._rng = np.random.default_rng()

    def __call__(self, img):
        if self._rng.random() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self._rng = np.random.default_rng()

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if self.padding:
            pad = [(0, 0)] * img.ndim
            if chw:
                pad[1] = pad[2] = (self.padding, self.padding)
            else:
                pad[0] = pad[1] = (self.padding, self.padding)
            img = np.pad(img, pad, mode="constant")
        h, w = img.shape[1:3] if chw else img.shape[:2]
        th, tw = self.size
        i = self._rng.integers(0, h - th + 1)
        j = self._rng.integers(0, w - tw + 1)
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h, w = img.shape[1:3] if chw else img.shape[:2]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


# -- 2nd wave: functional-backed transforms (ref transforms/transforms.py) --

from . import functional as F  # noqa: E402
functional = F

__all__ += ["functional", "RandomVerticalFlip", "RandomResizedCrop",
            "RandomRotation", "ColorJitter", "BrightnessTransform",
            "ContrastTransform", "SaturationTransform", "HueTransform",
            "Grayscale", "Pad", "RandomErasing"]


class RandomVerticalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob
        self._rng = np.random.default_rng()

    def __call__(self, img):
        if self._rng.random() < self.prob:
            return F.vflip(img)
        return img


class RandomResizedCrop:
    """Random area/aspect crop then resize (ref RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 interpolation: str = "bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation
        self._rng = np.random.default_rng()

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = arr.shape[1:3] if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = self._rng.uniform(*self.scale) * area
            log_r = self._rng.uniform(np.log(self.ratio[0]),
                                      np.log(self.ratio[1]))
            aspect = np.exp(log_r)
            tw = int(round(np.sqrt(target * aspect)))
            th = int(round(np.sqrt(target / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                i = int(self._rng.integers(0, h - th + 1))
                j = int(self._rng.integers(0, w - tw + 1))
                return F.resize(F.crop(arr, i, j, th, tw), self.size,
                                self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size,
                        self.interpolation)


class RandomRotation:
    def __init__(self, degrees, interpolation: str = "nearest",
                 expand: bool = False, center=None, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill
        self._rng = np.random.default_rng()

    def __call__(self, img):
        angle = float(self._rng.uniform(*self.degrees))
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class BrightnessTransform:
    def __init__(self, value: float):
        self.value = value
        self._rng = np.random.default_rng()

    def _factor(self):
        return float(self._rng.uniform(max(0, 1 - self.value),
                                       1 + self.value))

    def __call__(self, img):
        return F.adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        return F.adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        return F.adjust_saturation(img, self._factor())


class HueTransform:
    def __init__(self, value: float):
        assert 0 <= value <= 0.5
        self.value = value
        self._rng = np.random.default_rng()

    def __call__(self, img):
        return F.adjust_hue(img, float(self._rng.uniform(-self.value,
                                                         self.value)))


class ColorJitter:
    """Randomly-ordered brightness/contrast/saturation/hue jitter."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))
        self._rng = np.random.default_rng()

    def __call__(self, img):
        for idx in self._rng.permutation(len(self.transforms)):
            img = self.transforms[idx](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels: int = 1):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad:
    def __init__(self, padding, fill=0, padding_mode: str = "constant"):
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def __call__(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomErasing:
    """Random cutout rectangle (ref RandomErasing)."""

    def __init__(self, prob: float = 0.5, scale=(0.02, 0.33),
                 ratio=(0.3, 3.3), value=0):
        self.prob, self.scale, self.ratio, self.value = \
            prob, scale, ratio, value
        self._rng = np.random.default_rng()

    def __call__(self, img):
        if self._rng.random() >= self.prob:
            return img
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = arr.shape[1:3] if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = self._rng.uniform(*self.scale) * area
            aspect = np.exp(self._rng.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * aspect)))
            ew = int(round(np.sqrt(target / aspect)))
            if eh < h and ew < w:
                i = int(self._rng.integers(0, h - eh + 1))
                j = int(self._rng.integers(0, w - ew + 1))
                return F.erase(arr, i, j, eh, ew, self.value)
        return arr


# -- reference top-level functional re-exports + remaining classes ---------
from .functional import (to_tensor, hflip, vflip, resize, pad, affine,  # noqa: F401,E402
                         rotate, perspective, to_grayscale, crop,
                         center_crop, adjust_brightness, adjust_contrast,
                         adjust_hue, normalize, erase)


class BaseTransform:
    """ref transforms/transforms.py BaseTransform: keys-aware transform
    base — subclasses implement _apply_image (and optionally _apply_*)."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        outs = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, f"_apply_{key}", None)
            outs.append(fn(data) if fn else data)
        return tuple(outs)


class RandomAffine(BaseTransform):
    """ref RandomAffine: random rotation/translate/scale/shear."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        rng = np.random.default_rng()
        angle = rng.uniform(*self.degrees)
        arr = np.asarray(img)
        h, w = (arr.shape[:2] if arr.ndim == 2 or arr.shape[-1] <= 4
                else arr.shape[1:3])
        tx = ty = 0.0
        if self.translate is not None:
            tx = rng.uniform(-self.translate[0], self.translate[0]) * w
            ty = rng.uniform(-self.translate[1], self.translate[1]) * h
        sc = rng.uniform(*self.scale) if self.scale else 1.0
        sh = rng.uniform(*self.shear) if self.shear else 0.0
        return affine(img, angle, (tx, ty), sc, sh,
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    """ref RandomPerspective: random corner displacement warp."""

    def __init__(self, prob: float = 0.5, distortion_scale: float = 0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        rng = np.random.default_rng()
        if rng.random() >= self.prob:
            return img
        arr = np.asarray(img)
        h, w = (arr.shape[:2] if arr.ndim == 2 or arr.shape[-1] <= 4
                else arr.shape[1:3])
        d = self.distortion_scale
        dx = int(d * w / 2)
        dy = int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(rng.integers(0, dx + 1), rng.integers(0, dy + 1)),
               (w - 1 - rng.integers(0, dx + 1), rng.integers(0, dy + 1)),
               (w - 1 - rng.integers(0, dx + 1),
                h - 1 - rng.integers(0, dy + 1)),
               (rng.integers(0, dx + 1), h - 1 - rng.integers(0, dy + 1))]
        return perspective(img, start, end,
                           interpolation=self.interpolation, fill=self.fill)


__all__ += ["BaseTransform", "RandomAffine", "RandomPerspective",
            "to_tensor", "hflip", "vflip", "resize", "pad", "affine",
            "rotate", "perspective", "to_grayscale", "crop", "center_crop",
            "adjust_brightness", "adjust_contrast", "adjust_hue",
            "normalize", "erase"]
