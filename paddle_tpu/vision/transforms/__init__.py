"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy host-side
preprocessing; heavy augmentation pipelines belong in the input pipeline, not
on the TPU."""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "RandomHorizontalFlip",
           "RandomCrop", "CenterCrop", "Transpose"]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if img.max() > 1.0:
            img = img / 255.0
        if img.ndim == 2:
            img = img[None] if self.data_format == "CHW" else img[..., None]
        elif self.data_format == "CHW" and img.shape[-1] in (1, 3, 4):
            img = img.transpose(2, 0, 1)
        return img


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        try:
            import PIL.Image
            if isinstance(img, PIL.Image.Image):
                return np.asarray(img.resize(self.size[::-1]))
        except ImportError:
            pass
        # nearest-neighbor numpy resize
        img = np.asarray(img)
        h, w = img.shape[-2:] if img.ndim == 3 and img.shape[0] in (1, 3, 4) \
            else img.shape[:2]
        oh, ow = self.size
        ys = (np.arange(oh) * h / oh).astype(int)
        xs = (np.arange(ow) * w / ow).astype(int)
        if img.ndim == 3 and img.shape[0] in (1, 3, 4):
            return img[:, ys][:, :, xs]
        return img[ys][:, xs]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = prob
        self._rng = np.random.default_rng()

    def __call__(self, img):
        if self._rng.random() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self._rng = np.random.default_rng()

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if self.padding:
            pad = [(0, 0)] * img.ndim
            if chw:
                pad[1] = pad[2] = (self.padding, self.padding)
            else:
                pad[0] = pad[1] = (self.padding, self.padding)
            img = np.pad(img, pad, mode="constant")
        h, w = img.shape[1:3] if chw else img.shape[:2]
        th, tw = self.size
        i = self._rng.integers(0, h - th + 1)
        j = self._rng.integers(0, w - tw + 1)
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h, w = img.shape[1:3] if chw else img.shape[:2]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if chw:
            return img[:, i:i + th, j:j + tw]
        return img[i:i + th, j:j + tw]


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)
