"""Detection/vision operators (``paddle.vision.ops`` parity).

Reference: ``python/paddle/vision/ops.py`` (nms, roi_align, roi_pool,
box_coder, prior_box, yolo_box, distribute_fpn_proposals, read_file,
decode_jpeg — each backed by a fluid detection CUDA kernel). TPU-native
design notes:

- ``roi_align``/``roi_pool`` sample through
  ``jax.scipy.ndimage.map_coordinates`` (bilinear gather — XLA lowers it to
  dynamic-gathers that run well on TPU); sampling counts are static, per
  XLA's static-shape contract, so ``sampling_ratio=-1`` (adaptive in the
  CUDA kernel) resolves to a fixed 2 samples per bin axis.
- ``nms`` computes the pairwise-IoU suppression with a jittable
  ``lax.fori_loop`` over a keep mask; the final variable-length index
  extraction is host-side (detection postprocessing is eager in paddle
  too).
- ``distribute_fpn_proposals`` returns variable-length per-level splits and
  is therefore an eager (host) op.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.ndimage import map_coordinates

from ..nn.layer import Layer as _Layer

__all__ = [
    "RoIAlign", "RoIPool", "psroi_pool", "PSRoIPool", "yolo_loss",
    "generate_proposals","nms", "roi_align", "roi_pool", "box_coder", "prior_box",
           "yolo_box", "distribute_fpn_proposals", "read_file",
           "decode_jpeg"]


def _pairwise_iou_np(boxes: np.ndarray, offset: float = 0.0) -> np.ndarray:
    """Host-side [N, 4] xyxy -> [N, N] IoU (offset=1 for the
    integer-coordinate normalized=False convention)."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1 + offset, 0) * np.maximum(y2 - y1 + offset, 0)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(ix2 - ix1 + offset, 0) * \
        np.maximum(iy2 - iy1 + offset, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def _pairwise_iou(boxes):
    """[N, 4] xyxy -> [N, N] IoU."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def _nms_keep_mask(boxes, scores, iou_threshold: float):
    """Jittable greedy NMS keep mask over score-sorted boxes."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    ious = _pairwise_iou(boxes[order])

    def body(i, keep):
        sup = keep[i] & (ious[i] > iou_threshold) & (jnp.arange(n) > i)
        return keep & ~sup

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    return order, keep_sorted


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories: Optional[Sequence[int]] = None,
        top_k: Optional[int] = None):
    """Greedy hard NMS (ref ``vision/ops.py`` nms). ``boxes`` [N, 4] xyxy.
    Returns kept indices sorted by descending score. With
    ``category_idxs``/``categories``, suppression is per category (the
    standard coordinate-offset trick)."""
    boxes = jnp.asarray(boxes)
    n = boxes.shape[0]
    if scores is None:
        scores_arr = jnp.arange(n, 0, -1, dtype=jnp.float32)  # keep order
    else:
        scores_arr = jnp.asarray(scores, jnp.float32)
    nms_boxes = boxes
    if category_idxs is not None:
        # Shift each category into its own coordinate island so cross-
        # category pairs never overlap.
        cat = jnp.asarray(category_idxs)
        span = jnp.max(boxes) - jnp.min(boxes) + 1.0
        nms_boxes = boxes + (cat.astype(boxes.dtype) * span)[:, None]
    order, keep_sorted = _nms_keep_mask(nms_boxes, scores_arr, iou_threshold)
    kept = np.asarray(order)[np.asarray(keep_sorted)]
    if top_k is not None:
        kept = kept[:top_k]
    return jnp.asarray(kept)


def _roi_images(boxes_num, num_rois: int):
    """Per-roi image index from the per-image roi counts."""
    if boxes_num is None:
        return jnp.zeros((num_rois,), jnp.int32)
    boxes_num = jnp.asarray(boxes_num, jnp.int32)
    return jnp.repeat(jnp.arange(boxes_num.shape[0], dtype=jnp.int32),
                      boxes_num, total_repeat_length=num_rois)


def _roi_sample(x, boxes, boxes_num, output_size, spatial_scale,
                sampling_ratio, aligned, reduce):
    """Shared RoIAlign/RoIPool sampler: S x S bilinear samples per output
    bin, reduced by mean (align) or max (pool)."""
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    S = sampling_ratio if sampling_ratio and sampling_ratio > 0 else 2
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    num_rois = boxes.shape[0]
    img_ids = _roi_images(boxes_num, num_rois)
    offset = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    if not aligned:  # legacy: force rois to be at least 1x1
        x2 = jnp.maximum(x2, x1 + 1.0)
        y2 = jnp.maximum(y2, y1 + 1.0)
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw
    # Sample coordinates [R, ph*S] / [R, pw*S].
    sy = (jnp.arange(ph * S) + 0.5) / S   # in bin units
    sx = (jnp.arange(pw * S) + 0.5) / S
    ys = y1[:, None] + bin_h[:, None] * sy[None, :]
    xs = x1[:, None] + bin_w[:, None] * sx[None, :]

    def sample_roi(img_id, ys_r, xs_r):
        yy = jnp.broadcast_to(ys_r[:, None], (ph * S, pw * S))
        xx = jnp.broadcast_to(xs_r[None, :], (ph * S, pw * S))

        def per_channel(chan):
            return map_coordinates(chan, [yy, xx], order=1, mode="constant",
                                   cval=0.0)

        return jax.vmap(per_channel)(x[img_id])   # [C, ph*S, pw*S]

    samples = jax.vmap(sample_roi)(img_ids, ys, xs)  # [R, C, ph*S, pw*S]
    c = x.shape[1]
    samples = samples.reshape(num_rois, c, ph, S, pw, S)
    if reduce == "max":
        return samples.max(axis=(3, 5))
    return samples.mean(axis=(3, 5))


def roi_align(x, boxes, boxes_num=None, output_size=1,
              spatial_scale: float = 1.0, sampling_ratio: int = -1,
              aligned: bool = True, name=None):
    """RoIAlign (ref ``vision/ops.py`` roi_align): averaged bilinear samples
    per output bin. ``x`` [N, C, H, W]; ``boxes`` [R, 4] xyxy in input
    coords; ``boxes_num`` [N] rois per image."""
    return _roi_sample(x, boxes, boxes_num, output_size, spatial_scale,
                       sampling_ratio, aligned, "mean")


def roi_pool(x, boxes, boxes_num=None, output_size=1,
             spatial_scale: float = 1.0, name=None):
    """RoIPool (max). The CUDA kernel maxes over every integer pixel in a
    bin; with static shapes this maxes over a fixed 2x2 bilinear sample
    grid per bin — equal for bins <= 2px and a tight approximation above."""
    return _roi_sample(x, boxes, boxes_num, output_size, spatial_scale,
                       2, False, "max")


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0, name=None):
    """Encode/decode boxes against priors (ref fluid box_coder op).

    encode: target [M, 4] xyxy vs priors [M, 4] -> offsets [M, 4]
    decode: offsets [M, 4] + priors -> boxes [M, 4] xyxy
    """
    prior = jnp.asarray(prior_box, jnp.float32)
    target = jnp.asarray(target_box, jnp.float32)
    var = (jnp.asarray(prior_box_var, jnp.float32)
           if prior_box_var is not None else jnp.ones((4,), jnp.float32))
    norm = 0.0 if box_normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + norm
    ph = prior[:, 3] - prior[:, 1] + norm
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + norm
        th = target[:, 3] - target[:, 1] + norm
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(jnp.maximum(tw / pw, 1e-10)),
                         jnp.log(jnp.maximum(th / ph, 1e-10))], axis=1)
        return out / var.reshape(-1, 4)
    if code_type == "decode_center_size":
        d = target * var.reshape(-1, 4)
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w = jnp.exp(d[:, 2]) * pw
        h = jnp.exp(d[:, 3]) * ph
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=1)
    raise ValueError(f"code_type must be encode/decode_center_size, got "
                     f"{code_type!r}")


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip: bool = False, clip: bool = False, steps=(0.0, 0.0),
              offset: float = 0.5, min_max_aspect_ratios_order: bool = False,
              name=None):
    """SSD prior (anchor) boxes for one feature map (ref fluid prior_box).
    Returns (boxes [H, W, A, 4] xyxy-normalized, variances same shape)."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ratios = list(aspect_ratios)
    if flip:
        ratios += [1.0 / r for r in aspect_ratios if r != 1.0]
    whs = []
    for i, ms in enumerate(min_sizes):
        per_ms = [(ms * np.sqrt(r), ms / np.sqrt(r)) for r in ratios]
        if max_sizes:
            mx = max_sizes[i]
            max_box = (np.sqrt(ms * mx), np.sqrt(ms * mx))
            if min_max_aspect_ratios_order:
                # ref ordering flag: [min(ratio=1), max, remaining ratios]
                per_ms = per_ms[:1] + [max_box] + per_ms[1:]
            else:
                per_ms = per_ms + [max_box]
        whs.extend(per_ms)
    whs = jnp.asarray(whs, jnp.float32)                 # [A, 2]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cxg, cyg = jnp.meshgrid(cx, cy)                      # [H, W]
    centers = jnp.stack([cxg, cyg], axis=-1)[:, :, None, :]  # [H, W, 1, 2]
    half = whs[None, None, :, :] * 0.5
    mins = (centers - half) / jnp.asarray([iw, ih], jnp.float32)
    maxs = (centers + half) / jnp.asarray([iw, ih], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], axis=-1)       # [H, W, A, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return boxes, var


def yolo_box(x, img_size, anchors, class_num: int, conf_thresh: float = 0.01,
             downsample_ratio: int = 32, clip_bbox: bool = True,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5, name=None):
    """Decode one YOLOv3 head (ref ``vision/ops.py`` yolo_box).

    x: [N, A*(5+C), H, W]; img_size [N, 2] (h, w).
    Returns (boxes [N, H*W*A, 4] xyxy in image coords,
    scores [N, H*W*A, C]); below-threshold entries are zeroed (static
    shapes; the CUDA kernel zeroes too).
    """
    x = jnp.asarray(x, jnp.float32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    ioup = None
    if iou_aware:
        # PP-YOLO layout [N, A*(6+C), H, W]: first A channels are the IoU
        # predictions, the rest the standard head.
        ioup = jax.nn.sigmoid(x[:, :na].reshape(n, na, h, w))
        x = x[:, na:]
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)
    gy = jnp.arange(h, dtype=jnp.float32)
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (alpha * jax.nn.sigmoid(x[:, :, 0]) + beta
          + gx[None, None, None, :]) / w                      # [N,A,H,W]
    by = (alpha * jax.nn.sigmoid(x[:, :, 1]) + beta
          + gy[None, None, :, None]) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    if ioup is not None:
        conf = conf ** (1.0 - iou_aware_factor) * ioup ** iou_aware_factor
    probs = jax.nn.sigmoid(x[:, :, 5:])                       # [N,A,C,H,W]
    scores = conf[:, :, None] * probs
    keep = (conf > conf_thresh)[:, :, None]
    scores = jnp.where(keep, scores, 0.0)
    img_h = jnp.asarray(img_size, jnp.float32)[:, 0]
    img_w = jnp.asarray(img_size, jnp.float32)[:, 1]
    sx = img_w[:, None, None, None]
    sy = img_h[:, None, None, None]
    x1 = (bx - bw * 0.5) * sx
    y1 = (by - bh * 0.5) * sy
    x2 = (bx + bw * 0.5) * sx
    y2 = (by + bh * 0.5) * sy
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, sx - 1)
        y1 = jnp.clip(y1, 0.0, sy - 1)
        x2 = jnp.clip(x2, 0.0, sx - 1)
        y2 = jnp.clip(y2, 0.0, sy - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)              # [N,A,H,W,4]
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(n, -1, 4)
    scores = scores.transpose(0, 3, 4, 1, 2).reshape(n, -1, class_num)
    return boxes, scores


def distribute_fpn_proposals(fpn_rois, min_level: int, max_level: int,
                             refer_level: int, refer_scale: int,
                             pixel_offset: bool = False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (ref fluid
    distribute_fpn_proposals): level = refer + log2(sqrt(area)/scale).
    Variable-length outputs -> host-side op. Returns (per-level roi list,
    restore_index [R, 1])."""
    rois = np.asarray(fpn_rois, np.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, order = [], []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        outs.append(jnp.asarray(rois[idx]))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return outs, jnp.asarray(restore.reshape(-1, 1))


def read_file(path: str, name=None):
    """Raw file bytes as a uint8 tensor (ref ``vision/ops.py`` read_file)."""
    with open(path, "rb") as f:
        return jnp.asarray(np.frombuffer(f.read(), np.uint8))


def decode_jpeg(x, mode: str = "unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8 (ref decode_jpeg; the
    CUDA build uses nvJPEG — here PIL does the host-side decode)."""
    import io

    from ..utils import try_import
    Image = try_import("PIL.Image")
    img = Image.open(io.BytesIO(np.asarray(x).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups: int = 1, groups: int = 1,
                  mask=None, name=None):
    """Deformable convolution v1/v2 (ref ``vision/ops.py`` deform_conv2d →
    ``fluid/operators/deformable_conv_op``).

    x [N, Cin, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] with channel 2k = Δy
    and 2k+1 = Δx of tap k; mask (v2) [N, dg*kh*kw, Ho, Wo]. Sampling is
    bilinear via map_coordinates (XLA gathers); taps/channels vectorize
    with vmap — no im2col buffer.
    """
    from ..nn.functional import _pair
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    n, cin, h, w = x.shape
    cout, cpg, kh, kw = weight.shape
    k = kh * kw
    dg = deformable_groups
    offset = jnp.asarray(offset, jnp.float32)
    ho, wo = offset.shape[2], offset.shape[3]
    if offset.shape[1] != 2 * dg * k:
        raise ValueError(
            f"offset channels {offset.shape[1]} != 2*dg*kh*kw = {2 * dg * k}")
    # Base sampling grid per tap: [k, Ho, Wo]
    ys = (jnp.arange(ho) * sh - ph)[None, :, None] + \
        (jnp.arange(kh) * dh).repeat(kw)[:, None, None]
    xs = (jnp.arange(wo) * sw - pw)[None, None, :] + \
        jnp.tile(jnp.arange(kw) * dw, kh)[:, None, None]
    off = offset.reshape(n, dg, k, 2, ho, wo)
    py = ys[None, None] + off[:, :, :, 0]          # [N, dg, k, Ho, Wo]
    px = xs[None, None] + off[:, :, :, 1]
    if mask is not None:
        m = jnp.asarray(mask, jnp.float32).reshape(n, dg, k, ho, wo)
    else:
        m = jnp.ones((n, dg, k, ho, wo), jnp.float32)

    ch_per_dg = cin // dg

    def sample_image(xi, pyi, pxi, mi):
        # xi [Cin, H, W]; pyi/pxi/mi [dg, k, Ho, Wo]
        def per_channel(c):
            g = c // ch_per_dg
            vals = map_coordinates(xi[c].astype(jnp.float32),
                                   [pyi[g], pxi[g]], order=1,
                                   mode="constant", cval=0.0)
            return vals * mi[g]                     # [k, Ho, Wo]
        return jax.vmap(per_channel)(jnp.arange(cin))  # [Cin, k, Ho, Wo]

    sampled = jax.vmap(sample_image)(x, py, px, m)   # [N, Cin, k, Ho, Wo]
    wk = weight.reshape(cout, cpg, k).astype(jnp.float32)
    if groups == 1:
        out = jnp.einsum("nckhw,ock->nohw", sampled, wk)
    else:
        outs = []
        cout_g = cout // groups
        for g in range(groups):
            sg = sampled[:, g * cpg:(g + 1) * cpg]
            wg = wk[g * cout_g:(g + 1) * cout_g]
            outs.append(jnp.einsum("nckhw,ock->nohw", sg, wg))
        out = jnp.concatenate(outs, axis=1)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(x.dtype)


class DeformConv2D(_Layer):
    """Layer wrapper over :func:`deform_conv2d` (ref ``vision/ops.py``
    DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from ..nn import initializer as I
        from ..nn.functional import _pair

        super().__init__()
        kh, kw = _pair(kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        fan_in = in_channels // groups * kh * kw
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw),
            attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = (None if bias_attr is False else
                     self.create_parameter((out_channels,), attr=bias_attr,
                                           is_bias=True))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, stride=self.stride,
            padding=self.padding, dilation=self.dilation,
            deformable_groups=self.deformable_groups, groups=self.groups,
            mask=mask)


def matrix_nms(bboxes, scores, score_threshold: float, post_threshold: float,
               nms_top_k: int, keep_top_k: int, use_gaussian: bool = False,
               gaussian_sigma: float = 2.0, background_label: int = 0,
               normalized: bool = True, return_index: bool = False,
               return_rois_num: bool = True, name=None):
    """Matrix NMS (ref ``vision/ops.py`` matrix_nms, SOLOv2): instead of
    hard suppression, each box's score decays by the worst overlap with any
    higher-scored box of its class. Variable-length output -> host-side op.

    bboxes [N, M, 4]; scores [N, C, M]. Returns (out [R, 6]
    (label, score, x1, y1, x2, y2), index [R, 1] if requested,
    rois_num [N]).
    """
    bboxes_np = np.asarray(bboxes, np.float32)
    scores_np = np.asarray(scores, np.float32)
    n, c, m = scores_np.shape
    outs, idxs, counts = [], [], []
    for b in range(n):
        per_img = []
        per_idx = []
        for cls in range(c):
            if cls == background_label:
                continue
            s = scores_np[b, cls]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-s[sel])][:nms_top_k]
            iou = _pairwise_iou_np(bboxes_np[b, order],
                                   offset=0.0 if normalized else 1.0)
            iou = np.triu(iou, 1)        # iou[i, j], i higher-scored than j
            # SOLOv2 matrix decay: decay_j = min_i f(iou_ij) / f(comp_i),
            # comp_i = box i's own worst overlap with anything above it
            # (= column max of the upper triangle).
            comp = iou.max(axis=0)

            def f(x):
                return np.exp(-(x ** 2) / gaussian_sigma) if use_gaussian \
                    else 1.0 - x

            ratio = f(iou) / np.maximum(f(comp)[:, None], 1e-12)
            tri = np.triu(np.ones_like(iou, bool), 1)
            ratio = np.where(tri, ratio, np.inf)
            decay = np.minimum(ratio.min(axis=0, initial=np.inf), 1.0)
            new_scores = s[order] * decay
            keep = new_scores > post_threshold
            for i, ok in zip(range(len(order)), keep):
                if ok:
                    per_img.append((float(cls), float(new_scores[i]),
                                    *bboxes_np[b, order[i]].tolist()))
                    per_idx.append(b * m + order[i])
        if per_img:
            pack = sorted(zip(per_img, per_idx), key=lambda t: -t[0][1])
            pack = pack[:keep_top_k] if keep_top_k > 0 else pack
            per_img = [p for p, _ in pack]
            per_idx = [i for _, i in pack]
        outs.extend(per_img)
        idxs.extend(per_idx)
        counts.append(len(per_img))
    out = jnp.asarray(np.asarray(outs, np.float32).reshape(-1, 6))
    result = [out]
    if return_index:
        result.append(jnp.asarray(np.asarray(idxs, np.int64).reshape(-1, 1)))
    if return_rois_num:
        result.append(jnp.asarray(np.asarray(counts, np.int64)))
    return tuple(result) if len(result) > 1 else out


__all__ += ["deform_conv2d", "DeformConv2D", "matrix_nms"]


class RoIAlign:
    """Layer form of roi_align (ref vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num=None):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    """Layer form of roi_pool (ref vision/ops.py RoIPool)."""

    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num=None):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num=None, output_size=7,
               spatial_scale: float = 1.0, name=None):
    """Position-sensitive RoI pooling (ref vision/ops.py psroi_pool):
    channel group (i, j) feeds output bin (i, j) — x has C = out_c*ph*pw
    channels, output [R, out_c, ph, pw]."""
    x = jnp.asarray(x)
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    c = x.shape[1]
    if c % (ph * pw):
        raise ValueError(f"channels {c} not divisible by {ph}*{pw}")
    out_c = c // (ph * pw)
    # full RoIAlign on every channel, then pick the bin-matched group
    full = roi_align(x, boxes, boxes_num, (ph, pw), spatial_scale)
    r = full.shape[0]
    full = full.reshape(r, out_c, ph, pw, ph, pw)
    ii = jnp.arange(ph)
    jj = jnp.arange(pw)
    return full[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]


class PSRoIPool:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num=None):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth: bool = True, name=None, scale_x_y: float = 1.0):
    """YOLOv3 loss (ref vision/ops.py yolo_loss / fluid yolov3_loss op).

    x [N, mask*(5+cls), H, W]; gt_box [N, B, 4] (cx, cy, w, h, normalized);
    gt_label [N, B]. Per-cell anchor-matched objectness/box/class losses,
    summed per image (simplified single-scale assignment: each gt matches
    the best-IoU anchor in its cell, the standard v3 rule)."""
    x = jnp.asarray(x, jnp.float32)
    gt_box = jnp.asarray(gt_box, jnp.float32)
    gt_label = jnp.asarray(gt_label, jnp.int32)
    n, _, h, w = x.shape
    m = len(anchor_mask)
    x = x.reshape(n, m, 5 + class_num, h, w)
    tx, ty = x[:, :, 0], x[:, :, 1]
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]
    masked = [(anchors[2 * i], anchors[2 * i + 1]) for i in anchor_mask]
    aw = jnp.asarray([a[0] for a in masked], jnp.float32)
    ah = jnp.asarray([a[1] for a in masked], jnp.float32)
    stride = downsample_ratio
    in_w, in_h = w * stride, h * stride

    # build targets per gt: cell + best anchor
    bs = gt_box.shape[1]
    obj_target = jnp.zeros((n, m, h, w))
    loss = jnp.zeros((n,))
    valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0)
    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)
    gw = gt_box[:, :, 2] * in_w
    gh = gt_box[:, :, 3] * in_h
    inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
    union = gw[..., None] * gh[..., None] + aw * ah - inter
    best_a = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N, B]

    batch_idx = jnp.arange(n)[:, None].repeat(bs, 1)
    sel = (batch_idx, best_a, gj, gi)
    vf = valid.astype(jnp.float32)
    if gt_score is not None:
        # mixup score weighting (ref yolov3_loss: every positive-sample
        # loss term is scaled by the gt's mixup score)
        vf = vf * jnp.asarray(gt_score, jnp.float32)
    txy_t_x = gt_box[:, :, 0] * w - gi
    txy_t_y = gt_box[:, :, 1] * h - gj
    twh_t_w = jnp.log(jnp.maximum(gw / aw[best_a], 1e-9))
    twh_t_h = jnp.log(jnp.maximum(gh / ah[best_a], 1e-9))
    import jax.nn as jnn
    from jax import lax
    sx = jnn.sigmoid(tx[sel])
    sy = jnn.sigmoid(ty[sel])
    box_l = vf * ((sx - txy_t_x) ** 2 + (sy - txy_t_y) ** 2 +
                  (tw[sel] - twh_t_w) ** 2 + (th[sel] - twh_t_h) ** 2)
    smooth = (1.0 / class_num if use_label_smooth else 0.0)
    cls_t = jnn.one_hot(gt_label, class_num) * (1 - 2 * smooth) + smooth
    cls_logit = jnp.moveaxis(tcls, 2, -1)[sel]       # [N, B, cls]
    cls_l = vf * jnp.sum(
        jnp.maximum(cls_logit, 0) - cls_logit * cls_t +
        jnp.log1p(jnp.exp(-jnp.abs(cls_logit))), axis=-1)
    obj_target = obj_target.at[sel].max(vf)

    # ignore mask (ref yolov3_loss CalcObjnessLoss): a non-matched cell
    # whose decoded box overlaps ANY gt with IoU > ignore_thresh is
    # excluded from the negative objectness BCE
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    px = lax.stop_gradient((grid_x + jnn.sigmoid(tx)) / w)
    py = lax.stop_gradient((grid_y + jnn.sigmoid(ty)) / h)
    pw = lax.stop_gradient(aw[None, :, None, None] * jnp.exp(tw) / in_w)
    phh = lax.stop_gradient(ah[None, :, None, None] * jnp.exp(th) / in_h)
    gx1 = (gt_box[:, :, 0] - gt_box[:, :, 2] / 2)[:, None, None, None, :]
    gy1 = (gt_box[:, :, 1] - gt_box[:, :, 3] / 2)[:, None, None, None, :]
    gx2 = (gt_box[:, :, 0] + gt_box[:, :, 2] / 2)[:, None, None, None, :]
    gy2 = (gt_box[:, :, 1] + gt_box[:, :, 3] / 2)[:, None, None, None, :]
    iw = jnp.clip(jnp.minimum((px + pw / 2)[..., None], gx2)
                  - jnp.maximum((px - pw / 2)[..., None], gx1), 0)
    ih = jnp.clip(jnp.minimum((py + phh / 2)[..., None], gy2)
                  - jnp.maximum((py - phh / 2)[..., None], gy1), 0)
    inter_p = iw * ih
    union_p = (pw * phh)[..., None] + (gt_box[:, :, 2] * gt_box[:, :, 3]
                                       )[:, None, None, None, :] - inter_p
    iou_p = jnp.where(valid[:, None, None, None, :],
                      inter_p / jnp.maximum(union_p, 1e-9), 0.0)
    best_iou = jnp.max(iou_p, axis=-1)               # [N, m, h, w]
    obj_weight = jnp.where((best_iou > ignore_thresh) & (obj_target <= 0),
                           0.0, 1.0)
    obj_ce = obj_weight * (jnp.maximum(tobj, 0) - tobj * obj_target +
                           jnp.log1p(jnp.exp(-jnp.abs(tobj))))
    loss = jnp.sum(box_l + cls_l, axis=1) + jnp.sum(obj_ce, axis=(1, 2, 3))
    return loss


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n: int = 6000, post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.5, min_size: float = 0.1,
                       eta: float = 1.0, pixel_offset: bool = False,
                       return_rois_num: bool = False, name=None):
    """RPN proposal generation (ref vision/ops.py generate_proposals):
    decode anchors by deltas, clip, filter small, NMS, top-k. Host-side
    index construction (data-dependent sizes), jax compute."""
    import numpy as np
    scores = jnp.asarray(scores, jnp.float32)      # [N, A, H, W]
    deltas = jnp.asarray(bbox_deltas, jnp.float32)  # [N, 4A, H, W]
    anchors_f = jnp.asarray(anchors, jnp.float32).reshape(-1, 4)
    var = jnp.asarray(variances, jnp.float32).reshape(-1, 4)
    n = scores.shape[0]
    all_rois, all_scores, rois_num = [], [], []
    for i in range(n):
        sc = scores[i].transpose(1, 2, 0).reshape(-1)
        dl = deltas[i].reshape(-1, 4, scores.shape[2],
                               scores.shape[3]).transpose(2, 3, 0, 1)
        dl = dl.reshape(-1, 4)
        k = min(int(pre_nms_top_n), sc.shape[0])
        top = jnp.argsort(-sc)[:k]
        sc_k, dl_k = sc[top], dl[top]
        an_k, var_k = anchors_f[top % anchors_f.shape[0]], \
            var[top % var.shape[0]]
        aw = an_k[:, 2] - an_k[:, 0] + (1.0 if pixel_offset else 0.0)
        ah = an_k[:, 3] - an_k[:, 1] + (1.0 if pixel_offset else 0.0)
        acx = an_k[:, 0] + aw / 2
        acy = an_k[:, 1] + ah / 2
        cx = var_k[:, 0] * dl_k[:, 0] * aw + acx
        cy = var_k[:, 1] * dl_k[:, 1] * ah + acy
        bw = aw * jnp.exp(jnp.minimum(var_k[:, 2] * dl_k[:, 2], 10.0))
        bh = ah * jnp.exp(jnp.minimum(var_k[:, 3] * dl_k[:, 3], 10.0))
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2, cy + bh / 2], axis=1)
        hmax = jnp.asarray(img_size[i][0], jnp.float32)
        wmax = jnp.asarray(img_size[i][1], jnp.float32)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, wmax), jnp.clip(boxes[:, 1], 0, hmax),
            jnp.clip(boxes[:, 2], 0, wmax), jnp.clip(boxes[:, 3], 0, hmax),
        ], axis=1)
        keep_sz = ((boxes[:, 2] - boxes[:, 0] >= min_size) &
                   (boxes[:, 3] - boxes[:, 1] >= min_size))
        sc_k = jnp.where(keep_sz, sc_k, -jnp.inf)
        keep = nms(boxes, nms_thresh, scores=sc_k,
                   top_k=int(post_nms_top_n))
        # drop sub-min_size boxes that survived only because fewer than
        # post_nms_top_n valid candidates existed (their score is -inf)
        keep = keep[np.asarray(sc_k[keep]) > -np.inf]
        all_rois.append(boxes[keep])
        all_scores.append(sc_k[keep])
        rois_num.append(np.asarray(keep).shape[0])
    rois = jnp.concatenate(all_rois)
    rscores = jnp.concatenate(all_scores)
    if return_rois_num:
        return rois, rscores, jnp.asarray(rois_num, jnp.int32)
    return rois, rscores
