"""MobileNetV1 (ref: python/paddle/vision/models/mobilenetv1.py) —
depthwise-separable convolutions. Depthwise = grouped conv with
groups == channels, which XLA lowers to an MXU-friendly batched form."""

from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


def _conv_bn(in_ch, out_ch, kernel, stride=1, padding=0, groups=1):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, kernel, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_ch),
        nn.ReLU(),
    )


def _depthwise_separable(in_ch, out_ch, stride):
    return nn.Sequential(
        _conv_bn(in_ch, in_ch, 3, stride=stride, padding=1, groups=in_ch),
        _conv_bn(in_ch, out_ch, 1),
    )


class MobileNetV1(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        # (out_channels, stride) per depthwise-separable stage.
        plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
                (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
                (1024, 1)]
        blocks = [_conv_bn(3, c(32), 3, stride=2, padding=1)]
        in_ch = c(32)
        for out, stride in plan:
            blocks.append(_depthwise_separable(in_ch, c(out), stride))
            in_ch = c(out)
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained: bool = False, scale: float = 1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
