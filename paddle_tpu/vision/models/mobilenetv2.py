"""MobileNetV2 (ref: python/paddle/vision/models/mobilenetv2.py) —
inverted residuals with linear bottlenecks."""

from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_ch * expand_ratio))
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(in_ch, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [
            nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                      groups=hidden, bias_attr=False),
            nn.BatchNorm2D(hidden), nn.ReLU6(),
            nn.Conv2D(hidden, out_ch, 1, bias_attr=False),
            nn.BatchNorm2D(out_ch),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        # t (expansion), c (channels), n (repeats), s (first stride)
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_ch = _make_divisible(32 * scale)
        last_ch = _make_divisible(1280 * max(1.0, scale))
        blocks = [nn.Sequential(
            nn.Conv2D(3, in_ch, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_ch), nn.ReLU6())]
        for t, c, n, s in cfg:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                blocks.append(InvertedResidual(in_ch, out_ch,
                                               s if i == 0 else 1, t))
                in_ch = out_ch
        blocks.append(nn.Sequential(
            nn.Conv2D(in_ch, last_ch, 1, bias_attr=False),
            nn.BatchNorm2D(last_ch), nn.ReLU6()))
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained: bool = False, scale: float = 1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
