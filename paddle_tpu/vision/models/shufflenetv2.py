"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py)."""

from __future__ import annotations

from ... import nn
from ...tensor import concat

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


def _channel_shuffle(x, groups: int):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = x.transpose(0, 2, 1, 3, 4)
    return x.reshape(n, c, h, w)


def _conv_bn_act(in_ch, out_ch, kernel, stride=1, padding=0, groups=1,
                 act=None):
    layers = [nn.Conv2D(in_ch, out_ch, kernel, stride=stride, padding=padding,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_ch)]
    if act is not None:
        layers.append(act())
    return nn.Sequential(*layers)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act):
        super().__init__()
        self.stride = stride
        branch_ch = out_ch // 2
        if stride == 1:
            # input is split in half; right branch transforms its half
            self.branch2 = nn.Sequential(
                _conv_bn_act(in_ch // 2, branch_ch, 1, act=act),
                _conv_bn_act(branch_ch, branch_ch, 3, stride=1, padding=1,
                             groups=branch_ch),
                _conv_bn_act(branch_ch, branch_ch, 1, act=act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn_act(in_ch, in_ch, 3, stride=stride, padding=1,
                             groups=in_ch),
                _conv_bn_act(in_ch, branch_ch, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn_act(in_ch, branch_ch, 1, act=act),
                _conv_bn_act(branch_ch, branch_ch, 3, stride=stride,
                             padding=1, groups=branch_ch),
                _conv_bn_act(branch_ch, branch_ch, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        act_layer = nn.Silu if act == "swish" else nn.ReLU
        c0, c1, c2, c3, c_last = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = _conv_bn_act(3, c0, 3, stride=2, padding=1,
                                  act=act_layer)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = c0
        for out_ch, n in zip((c1, c2, c3), _REPEATS):
            units = [_ShuffleUnit(in_ch, out_ch, 2, act_layer)]
            units += [_ShuffleUnit(out_ch, out_ch, 1, act_layer)
                      for _ in range(n - 1)]
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn_act(in_ch, c_last, 1, act=act_layer)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c_last, num_classes)

    def forward(self, x):
        x = self.stages(self.maxpool(self.conv1(x)))
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained: bool = False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
