"""MobileNetV3 small/large (ref: python/paddle/vision/models/mobilenetv3.py)
— inverted residuals + squeeze-excite + hardswish."""

from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_factor=4):
        super().__init__()
        sq = _make_divisible(ch // squeeze_factor)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(ch, sq, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(sq, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _Bneck(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, kernel, stride, use_se,
                 use_hs):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        act = nn.Hardswish if use_hs else nn.ReLU
        layers = []
        if exp_ch != in_ch:
            layers += [nn.Conv2D(in_ch, exp_ch, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_ch), act()]
        layers += [
            nn.Conv2D(exp_ch, exp_ch, kernel, stride=stride,
                      padding=kernel // 2, groups=exp_ch, bias_attr=False),
            nn.BatchNorm2D(exp_ch), act(),
        ]
        if use_se:
            layers.append(SqueezeExcite(exp_ch))
        layers += [nn.Conv2D(exp_ch, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    # rows: kernel, expanded, out, use_se, use_hs, stride
    def __init__(self, cfg, last_exp, last_ch, scale, num_classes,
                 with_pool):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        in_ch = c(16)
        blocks = [nn.Sequential(
            nn.Conv2D(3, in_ch, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_ch), nn.Hardswish())]
        for k, exp, out, se, hs, s in cfg:
            blocks.append(_Bneck(in_ch, c(exp), c(out), k, s, se, hs))
            in_ch = c(out)
        blocks.append(nn.Sequential(
            nn.Conv2D(in_ch, c(last_exp), 1, bias_attr=False),
            nn.BatchNorm2D(c(last_exp)), nn.Hardswish()))
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_exp), last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        cfg = [
            (3, 16, 16, True, False, 2),
            (3, 72, 24, False, False, 2),
            (3, 88, 24, False, False, 1),
            (5, 96, 40, True, True, 2),
            (5, 240, 40, True, True, 1),
            (5, 240, 40, True, True, 1),
            (5, 120, 48, True, True, 1),
            (5, 144, 48, True, True, 1),
            (5, 288, 96, True, True, 2),
            (5, 576, 96, True, True, 1),
            (5, 576, 96, True, True, 1),
        ]
        super().__init__(cfg, 576, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        cfg = [
            (3, 16, 16, False, False, 1),
            (3, 64, 24, False, False, 2),
            (3, 72, 24, False, False, 1),
            (5, 72, 40, True, False, 2),
            (5, 120, 40, True, False, 1),
            (5, 120, 40, True, False, 1),
            (3, 240, 80, False, True, 2),
            (3, 200, 80, False, True, 1),
            (3, 184, 80, False, True, 1),
            (3, 184, 80, False, True, 1),
            (3, 480, 112, True, True, 1),
            (3, 672, 112, True, True, 1),
            (5, 672, 160, True, True, 2),
            (5, 960, 160, True, True, 1),
            (5, 960, 160, True, True, 1),
        ]
        super().__init__(cfg, 960, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained: bool = False, scale: float = 1.0,
                       **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained: bool = False, scale: float = 1.0,
                       **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
