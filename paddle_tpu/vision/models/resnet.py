"""ResNet family (ref: python/paddle/vision/models/resnet.py) — BASELINE
config 2 (ResNet-50 data-parallel ImageNet)."""

from __future__ import annotations

from typing import List, Optional, Type, Union

import inspect

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "resnext50_32x4d", "resnext50_64x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
           "resnext152_64x4d", "wide_resnet50_2", "wide_resnet101_2"]


def _norm(norm_layer, num_features, data_format):
    """Construct a norm layer, passing data_format only to callables that
    accept it (custom norm_layer callables may not)."""
    try:
        params = inspect.signature(norm_layer).parameters
        accepts = "data_format" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())
    except (TypeError, ValueError):
        accepts = False
    if accepts:
        return norm_layer(num_features, data_format=data_format)
    return norm_layer(num_features)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=df)
        self.bn1 = _norm(norm_layer, planes, df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = _norm(norm_layer, planes, df)
        self.downsample = downsample if downsample is not None else None
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = _norm(norm_layer, width, df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation,
                               bias_attr=False, data_format=df)
        self.bn2 = _norm(norm_layer, width, df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = _norm(norm_layer, planes * self.expansion, df)
        self.relu = nn.ReLU()
        self.downsample = downsample if downsample is not None else None

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth: int = 50, width: int = 64,
                 num_classes: int = 1000, with_pool: bool = True,
                 groups: int = 1, data_format: str = "NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        # NHWC puts channels on the TPU's 128-lane minor dim — convs tile
        # directly onto the MXU with no layout canonicalization passes.
        self.data_format = data_format

        df = data_format
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=df)
        self.bn1 = nn.BatchNorm2D(self.inplanes, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                nn.BatchNorm2D(planes * block.expansion, data_format=df),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, data_format=df))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained: bool = False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained: bool = False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


# ResNeXt: grouped 3x3 bottlenecks (ref resnet.py resnext* factories).
def resnext50_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=32, width=4, **kwargs)


def resnext50_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=64, width=4, **kwargs)


def resnext101_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=64, width=4, **kwargs)


def resnext152_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=32, width=4, **kwargs)


def resnext152_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=64, width=4, **kwargs)


# Wide ResNet: 2x bottleneck width (ref resnet.py wide_resnet*_2).
def wide_resnet50_2(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, width=128, **kwargs)
