"""ResNet family (ref: python/paddle/vision/models/resnet.py) — BASELINE
config 2 (ResNet-50 data-parallel ImageNet)."""

from __future__ import annotations

from typing import List, Optional, Type, Union

import inspect

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "resnext50_32x4d", "resnext50_64x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
           "resnext152_64x4d", "wide_resnet50_2", "wide_resnet101_2"]


def _norm(norm_layer, num_features, data_format):
    """Construct a norm layer, passing data_format only to callables that
    accept it (custom norm_layer callables may not)."""
    try:
        params = inspect.signature(norm_layer).parameters
        accepts = "data_format" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())
    except (TypeError, ValueError):
        accepts = False
    if accepts:
        return norm_layer(num_features, data_format=data_format)
    return norm_layer(num_features)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=df)
        self.bn1 = _norm(norm_layer, planes, df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = _norm(norm_layer, planes, df)
        self.downsample = downsample if downsample is not None else None
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = _norm(norm_layer, width, df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation,
                               bias_attr=False, data_format=df)
        self.bn2 = _norm(norm_layer, width, df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = _norm(norm_layer, planes * self.expansion, df)
        self.relu = nn.ReLU()
        self.downsample = downsample if downsample is not None else None

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


def _space_to_depth(x):
    """[N, H, W, C] -> [N, H/2, W/2, 4C], channel order (hb, wb, C)."""
    import jax.numpy as jnp
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // 2, w // 2, 4 * c)


def _fold_stem_weight(w):
    """conv1 [O, C, 7, 7] -> the equivalent 4x4 kernel [O, 4C, 4, 4] over
    space-to-depth input (pad to 8x8 top-left; split each spatial dim into
    (block, phase); phases become input channels)."""
    import jax.numpy as jnp
    o, c = w.shape[0], w.shape[1]
    w8 = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
    w8 = w8.reshape(o, c, 4, 2, 4, 2)            # (o, c, a, hb, b, wb)
    w2 = w8.transpose(0, 3, 5, 1, 2, 4)          # (o, hb, wb, c, a, b)
    return w2.reshape(o, 4 * c, 4, 4)


class ResNet(nn.Layer):
    """stem_mode='space_to_depth' (NHWC only) rewrites the 7x7/s2 stem conv
    as an exactly-equivalent 4x4/s1 conv on 2x2 space-to-depth input — the
    MLPerf TPU trick: 12 input channels instead of 3 stop the MXU padding
    waste of the C=3 convolution (weights folded on the fly, bitwise the
    same module parameters)."""

    def __init__(self, block, depth: int = 50, width: int = 64,
                 num_classes: int = 1000, with_pool: bool = True,
                 groups: int = 1, data_format: str = "NCHW",
                 stem_mode: str = "conv"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        # NHWC puts channels on the TPU's 128-lane minor dim — convs tile
        # directly onto the MXU with no layout canonicalization passes.
        self.data_format = data_format
        if stem_mode not in ("conv", "space_to_depth"):
            raise ValueError(f"stem_mode {stem_mode!r}")
        if stem_mode == "space_to_depth" and data_format != "NHWC":
            raise ValueError("space_to_depth stem requires NHWC")
        self.stem_mode = stem_mode

        df = data_format
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=df)
        self.bn1 = nn.BatchNorm2D(self.inplanes, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                nn.BatchNorm2D(planes * block.expansion, data_format=df),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, data_format=df))
        return nn.Sequential(*layers)

    def forward(self, x):
        if self.stem_mode == "space_to_depth":
            import jax.numpy as jnp
            from ...nn import functional as F
            xs = _space_to_depth(x)
            xs = jnp.pad(xs, ((0, 0), (2, 1), (2, 1), (0, 0)))
            w2 = _fold_stem_weight(self.conv1.weight)
            x = F.conv2d(xs, w2.astype(xs.dtype), stride=1, padding=0,
                         data_format="NHWC")
        else:
            x = self.conv1(x)
        x = self.maxpool(self.relu(self.bn1(x)))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained: bool = False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained: bool = False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


# ResNeXt: grouped 3x3 bottlenecks (ref resnet.py resnext* factories).
def resnext50_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=32, width=4, **kwargs)


def resnext50_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=64, width=4, **kwargs)


def resnext101_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=64, width=4, **kwargs)


def resnext152_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=32, width=4, **kwargs)


def resnext152_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=64, width=4, **kwargs)


# Wide ResNet: 2x bottleneck width (ref resnet.py wide_resnet*_2).
def wide_resnet50_2(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, width=128, **kwargs)
