"""ResNet family (ref: python/paddle/vision/models/resnet.py) — BASELINE
config 2 (ResNet-50 data-parallel ImageNet)."""

from __future__ import annotations

from typing import List, Optional, Type, Union

import inspect

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "resnext50_32x4d", "resnext50_64x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
           "resnext152_64x4d", "wide_resnet50_2", "wide_resnet101_2"]


def _fusable(block, x) -> bool:
    """The deferred-BN fused path (nn.fused_conv_bn) applies when training
    in NHWC with plain affine BatchNorm everywhere — the conditions under
    which the reference would dispatch cuDNN fused conv-BN-activation."""
    from ...nn import fused_conv_bn as FCB
    from ...nn.layers import _BatchNormBase
    if x.ndim != 4 or getattr(block, "_data_format", None) != "NHWC":
        return False
    if not block.training or not FCB.fused_conv_bn_enabled():
        return False
    bns = [block.bn1, block.bn2] + \
        ([block.bn3] if hasattr(block, "bn3") else [])
    if block.downsample is not None:
        if len(getattr(block.downsample, "_sub_layers", {})) != 2:
            return False
        bns.append(block.downsample[1])
    for bn in bns:
        if not isinstance(bn, _BatchNormBase) or bn.use_global_stats \
                or bn.weight is None or bn.bias is None:
            return False
    return True


def _fused_identity(block, x):
    """Downsample branch under the fused path: 1x1 strided conv with stats
    epilogue, BN applied from its own sums (no activation)."""
    from ...nn import fused_conv_bn as FCB
    if block.downsample is None:
        return x
    dconv, dbn = block.downsample[0], block.downsample[1]
    s = _pair(dconv.stride)
    od, sd, ssd = FCB.conv_stats(x, dconv.weight, s, _pair(dconv.padding),
                                 _pair(dconv.dilation), dconv.groups)
    FCB.update_bn_buffers(dbn, sd, ssd, od.size // od.shape[-1])
    return FCB.bn_act_from_stats(od, dbn.weight, dbn.bias, sd, ssd,
                                 dbn.epsilon, "none")


from ...nn.functional import _pair  # noqa: E402


def _norm(norm_layer, num_features, data_format):
    """Construct a norm layer, passing data_format only to callables that
    accept it (custom norm_layer callables may not)."""
    try:
        params = inspect.signature(norm_layer).parameters
        accepts = "data_format" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())
    except (TypeError, ValueError):
        accepts = False
    if accepts:
        return norm_layer(num_features, data_format=data_format)
    return norm_layer(num_features)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=df)
        self.bn1 = _norm(norm_layer, planes, df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = _norm(norm_layer, planes, df)
        self.downsample = downsample if downsample is not None else None
        self.stride = stride
        self._data_format = data_format

    def forward(self, x):
        if _fusable(self, x):
            return self._forward_fused(x)
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)

    def _forward_fused(self, x):
        from ...nn import fused_conv_bn as FCB
        o1, s1, ss1 = FCB.conv_stats(
            x, self.conv1.weight, _pair(self.conv1.stride), (1, 1))
        FCB.update_bn_buffers(self.bn1, s1, ss1, o1.size // o1.shape[-1])
        o2, s2, ss2 = FCB.conv_bn_act(
            o1, self.bn1.weight, self.bn1.bias, s1, ss1, self.conv2.weight,
            self.bn1.epsilon, "relu", (1, 1), (1, 1))
        FCB.update_bn_buffers(self.bn2, s2, ss2, o2.size // o2.shape[-1])
        identity = _fused_identity(self, x)
        return FCB.bn_add_act(o2, self.bn2.weight, self.bn2.bias, s2, ss2,
                              identity, self.bn2.epsilon)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = _norm(norm_layer, width, df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation,
                               bias_attr=False, data_format=df)
        self.bn2 = _norm(norm_layer, width, df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = _norm(norm_layer, planes * self.expansion, df)
        self.relu = nn.ReLU()
        self.downsample = downsample if downsample is not None else None
        self._data_format = data_format

    def forward(self, x):
        if _fusable(self, x):
            return self._forward_fused(x)
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)

    def _forward_fused(self, x):
        """Deferred-BN bottleneck: each conv consumes the previous conv's
        raw output with BN+ReLU as an in-fusion prologue and emits channel
        sums as an epilogue (nn.fused_conv_bn docstring has the full
        traffic story). Semantically identical to the plain forward."""
        from ...nn import fused_conv_bn as FCB
        c2 = self.conv2
        o1, s1, ss1 = FCB.conv_stats(x, self.conv1.weight)
        FCB.update_bn_buffers(self.bn1, s1, ss1, o1.size // o1.shape[-1])
        o2, s2, ss2 = FCB.conv_bn_act(
            o1, self.bn1.weight, self.bn1.bias, s1, ss1, c2.weight,
            self.bn1.epsilon, "relu", _pair(c2.stride), _pair(c2.padding),
            _pair(c2.dilation), c2.groups)
        FCB.update_bn_buffers(self.bn2, s2, ss2, o2.size // o2.shape[-1])
        o3, s3, ss3 = FCB.conv_bn_act(
            o2, self.bn2.weight, self.bn2.bias, s2, ss2, self.conv3.weight,
            self.bn2.epsilon, "relu")
        FCB.update_bn_buffers(self.bn3, s3, ss3, o3.size // o3.shape[-1])
        identity = _fused_identity(self, x)
        return FCB.bn_add_act(o3, self.bn3.weight, self.bn3.bias, s3, ss3,
                              identity, self.bn3.epsilon)


def _space_to_depth(x):
    """[N, H, W, C] -> [N, H/2, W/2, 4C], channel order (hb, wb, C)."""
    import jax.numpy as jnp
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // 2, w // 2, 4 * c)


def _fold_stem_weight(w):
    """conv1 [O, C, 7, 7] -> the equivalent 4x4 kernel [O, 4C, 4, 4] over
    space-to-depth input (pad to 8x8 top-left; split each spatial dim into
    (block, phase); phases become input channels)."""
    import jax.numpy as jnp
    o, c = w.shape[0], w.shape[1]
    w8 = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
    w8 = w8.reshape(o, c, 4, 2, 4, 2)            # (o, c, a, hb, b, wb)
    w2 = w8.transpose(0, 3, 5, 1, 2, 4)          # (o, hb, wb, c, a, b)
    return w2.reshape(o, 4 * c, 4, 4)


class ResNet(nn.Layer):
    """stem_mode='space_to_depth' (NHWC only) rewrites the 7x7/s2 stem conv
    as an exactly-equivalent 4x4/s1 conv on 2x2 space-to-depth input — the
    MLPerf TPU trick: 12 input channels instead of 3 stop the MXU padding
    waste of the C=3 convolution (weights folded on the fly, bitwise the
    same module parameters)."""

    def __init__(self, block, depth: int = 50, width: int = 64,
                 num_classes: int = 1000, with_pool: bool = True,
                 groups: int = 1, data_format: str = "NCHW",
                 stem_mode: str = "conv"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        # NHWC puts channels on the TPU's 128-lane minor dim — convs tile
        # directly onto the MXU with no layout canonicalization passes.
        self.data_format = data_format
        if stem_mode not in ("conv", "space_to_depth"):
            raise ValueError(f"stem_mode {stem_mode!r}")
        if stem_mode == "space_to_depth" and data_format != "NHWC":
            raise ValueError("space_to_depth stem requires NHWC")
        self.stem_mode = stem_mode

        df = data_format
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=df)
        self.bn1 = nn.BatchNorm2D(self.inplanes, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                nn.BatchNorm2D(planes * block.expansion, data_format=df),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, data_format=df))
        return nn.Sequential(*layers)

    def _stem_fusable(self, x) -> bool:
        from ...nn import fused_conv_bn as FCB
        from ...nn.layers import _BatchNormBase
        return (x.ndim == 4 and self.data_format == "NHWC" and self.training
                and FCB.fused_conv_bn_enabled()
                and isinstance(self.bn1, _BatchNormBase)
                and not self.bn1.use_global_stats
                and self.bn1.weight is not None
                and self.bn1.bias is not None)

    def forward(self, x):
        fused = self._stem_fusable(x)
        if self.stem_mode == "space_to_depth":
            import jax.numpy as jnp
            from ...nn import functional as F
            xs = _space_to_depth(x)
            xs = jnp.pad(xs, ((0, 0), (2, 1), (2, 1), (0, 0)))
            w2 = _fold_stem_weight(self.conv1.weight)
            if fused:
                x, stem_pad = xs, (0, 0)
                stem_w, stem_stride = w2, (1, 1)
            else:
                x = F.conv2d(xs, w2.astype(xs.dtype), stride=1, padding=0,
                             data_format="NHWC")
        elif fused:
            stem_w = self.conv1.weight
            stem_stride, stem_pad = _pair(self.conv1.stride), \
                _pair(self.conv1.padding)
        else:
            x = self.conv1(x)
        if fused:
            from ...nn import fused_conv_bn as FCB
            o0, s0, ss0 = FCB.conv_stats(x, stem_w, stem_stride, stem_pad)
            FCB.update_bn_buffers(self.bn1, s0, ss0,
                                  o0.size // o0.shape[-1])
            x = FCB.bn_act_from_stats(o0, self.bn1.weight, self.bn1.bias,
                                      s0, ss0, self.bn1.epsilon, "relu")
            x = self.maxpool(x)
        else:
            x = self.maxpool(self.relu(self.bn1(x)))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained: bool = False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained: bool = False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


# ResNeXt: grouped 3x3 bottlenecks (ref resnet.py resnext* factories).
def resnext50_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=32, width=4, **kwargs)


def resnext50_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=64, width=4, **kwargs)


def resnext101_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=64, width=4, **kwargs)


def resnext152_32x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=32, width=4, **kwargs)


def resnext152_64x4d(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=64, width=4, **kwargs)


# Wide ResNet: 2x bottleneck width (ref resnet.py wide_resnet*_2).
def wide_resnet50_2(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained: bool = False, **kwargs):
    return _resnet(BottleneckBlock, 101, width=128, **kwargs)
