"""DenseNet family (ref: python/paddle/vision/models/densenet.py)."""

from __future__ import annotations

from ... import nn
from ...tensor import concat

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFGS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers: int = 121, bn_size: int = 4,
                 dropout: float = 0.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if layers not in _CFGS:
            raise ValueError(f"layers must be one of {sorted(_CFGS)}")
        num_init, growth, block_cfg = _CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        feats = [nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))]
        ch = num_init
        for bi, n_layers in enumerate(block_cfg):
            for _ in range(n_layers):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats.append(nn.BatchNorm2D(ch))
        feats.append(nn.ReLU())
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def densenet121(pretrained: bool = False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained: bool = False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained: bool = False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained: bool = False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained: bool = False, **kwargs):
    return DenseNet(264, **kwargs)
