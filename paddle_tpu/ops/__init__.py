"""Custom TPU ops (Pallas kernels with jnp reference fallbacks).

This package is the analog of the reference's hand-written CUDA kernel layer
(``phi/kernels/gpu``, ``phi/kernels/fusion``, vendored flash-attention): the
small set of ops where XLA's automatic fusion isn't enough and a Pallas
kernel buys real throughput — flash attention (fwd+bwd), fused optimizer
update, ring-attention comm-compute overlap.
"""

from .flash_attention import flash_attention, flash_attn_unpadded  # noqa: F401
