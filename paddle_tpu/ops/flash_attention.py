"""Flash attention.

Reference: ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:324`` (FlashAttnKernel
dispatching to the vendored CUTLASS flash-attention; varlen variant at :289).

TPU-native: a Pallas kernel (``_pallas/flash_attention.py``) implementing the
standard online-softmax blocked algorithm tiled for the MXU (block sizes
multiples of 128), with a custom VJP whose backward is also a Pallas kernel.
Layout follows paddle's flash_attn: [batch, seq, heads, head_dim].
``FLAGS_use_pallas_kernels=0`` (or unsupported shapes/platform) falls back to
the jnp reference — numerically identical module-level semantics, used for
CPU tests and gradient checks.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import flags

__all__ = ["flash_attention", "flash_attn_unpadded", "reference_attention",
           "single_query_attention"]


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None,
                        bias: Optional[jax.Array] = None):
    """jnp reference, [B,S,H,D] layout, fp32 softmax. Handles grouped-query
    kv (fewer kv heads) and rows with no valid keys (output 0, matching the
    Pallas kernel)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), sk - sq)
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    # Masked-row-safe softmax: fully-masked rows (all -inf) produce 0, not
    # NaN — matching the Pallas kernels' handling.
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.where(jnp.isfinite(scores),
                  jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0)), 0.0)
    probs = (e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True),
                             1e-30)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def single_query_attention(q, k, v, lengths=None,
                           scale: Optional[float] = None):
    """Decode-step attention: one query position over gathered KV.

    ``q`` is ``[B, 1, H, D]``; ``k``/``v`` are ``[B, Sk, KH, D]`` with
    ``KH`` dividing ``H`` — grouped-query KV is read through a head
    reshape (query head ``h`` uses kv head ``h // (H // KH)``, the same
    mapping as ``jnp.repeat`` on the head axis) so no repeated KV is ever
    materialized. ``lengths`` (``[B]`` int, optional) masks each row to
    its first ``lengths[b]`` keys — the serving engine's per-sequence
    context lengths over a padded gathered-KV batch; a row with zero
    valid keys returns 0 (the kernels' masked-row convention).

    With ``lengths=None`` this equals ``reference_attention(q, k, v,
    causal=True)`` at Sq=1 (the last causal row sees every key), without
    the dense path's ``[Sq, Sk]`` mask build, head-repeat, or recompute
    of the full score matrix machinery.
    """
    b, sq, h, d = q.shape
    if sq != 1:
        raise ValueError(f"single_query_attention needs Sq=1, got {sq}")
    sk, kh = k.shape[1], k.shape[2]
    if h % kh:
        raise ValueError(f"query heads ({h}) not a multiple of kv heads "
                         f"({kh})")
    g = h // kh
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qg = q[:, 0].reshape(b, kh, g, d)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if lengths is not None:
        valid = jnp.arange(sk)[None, :] < jnp.asarray(lengths)[:, None]
        scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    # Masked-row-safe softmax, matching reference_attention.
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.where(jnp.isfinite(scores),
                  jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0)), 0.0)
    probs = (e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True),
                             1e-30)).astype(q.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v)
    return out.reshape(b, 1, h, d)


def _use_pallas(q, k) -> bool:
    if not flags.flag("use_pallas_kernels"):
        return False
    try:
        platform = q.devices().pop().platform if hasattr(q, "devices") \
            else jax.default_backend()
    except Exception:
        platform = jax.default_backend()
    if platform not in ("tpu", "axon"):
        return False
    # MXU-friendly shapes only (both seq lens tile-divisible); else the
    # reference path — the kernel would silently drop tail keys otherwise.
    from ._pallas.flash_attention import supported_shapes
    return supported_shapes(q, k)


def _dense_prob_dropout_attention(q, k, v, causal, scale, seed,
                                  rate: float):
    """Dense mirror of the kernel's attention-prob dropout: the SAME
    position-hashed mask (``dropout_keep_dense``), applied to the softmax
    probabilities (NOT the output — ref flash_attn_kernel.cu:44), so
    pallas and fallback paths agree bitwise under a fixed seed."""
    from ._pallas.flash_attention import dropout_keep_dense
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), sk - sq)
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.where(jnp.isfinite(scores),
                  jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0)), 0.0)
    probs = e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
    keep = dropout_keep_dense(b * h, sq, sk, seed, rate)  # [BH, Sq, Sk]
    probs = (probs * keep.reshape(b, h, sq, sk)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(query, key, value, dropout: float = 0.0,
                    causal: bool = False, return_softmax: bool = False,
                    *, scale: Optional[float] = None, training: bool = True,
                    fixed_seed_offset=None):
    """paddle.nn.functional.flash_attention parity ([B,S,H,D]).

    ``dropout`` is attention-PROB dropout inside the kernel (ref
    flash_attn_kernel.cu:44): the mask is regenerated in backward from
    (position, seed) — the TPU-native form of the reference's saved-RNG-
    state recompute (:76). ``fixed_seed_offset`` pins the seed."""
    if return_softmax:
        raise NotImplementedError("return_softmax is a debug-only GPU feature")
    if dropout > 0.0 and training:
        if fixed_seed_offset is not None:
            seed = jnp.asarray(fixed_seed_offset, jnp.int32).reshape(1)
        else:
            from ..core.random import next_key
            seed = jax.random.randint(next_key(), (1,), 0, 2 ** 31 - 1,
                                      dtype=jnp.int32)
        if _use_pallas(query, key):
            from ._pallas.flash_attention import flash_attention_pallas
            return flash_attention_pallas(query, key, value, causal=causal,
                                          scale=scale, dropout=dropout,
                                          dropout_seed=seed)
        return _dense_prob_dropout_attention(query, key, value, causal,
                                             scale, seed, dropout)
    if _use_pallas(query, key):
        from ._pallas.flash_attention import flash_attention_pallas
        return flash_attention_pallas(query, key, value, causal=causal,
                                      scale=scale)
    if query.shape[1] == 1:
        # Decode step (Sq=1): the dense reference path would rebuild the
        # causal mask and the full repeated-KV score machinery for a
        # single row whose causal mask is all-visible — route through
        # the single-query helper instead.
        return single_query_attention(query, key, value, scale=scale)
    return reference_attention(query, key, value, causal, scale)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q: int, max_seqlen_k: int,
                        scale: Optional[float] = None, dropout: float = 0.0,
                        causal: bool = False):
    """Varlen parity (ref flash_attn_kernel.cu:289). XLA needs static
    shapes, so varlen is expressed with static totals (SURVEY §7
    hard-part (c)):

    - **fast path** (self-attention, tile-divisible packed length): run the
      Pallas kernel directly on the packed [1, total, H, D] layout with
      per-token segment ids — no padding FLOPs at all;
    - fallback: scatter to a padded batch + segment-mask dense reference.
    """
    b = cu_seqlens_q.shape[0] - 1
    total_q, h, d = query.shape
    # Causal masking in the packed kernel uses global positions, which
    # equals per-sequence causality only when q and k share boundaries;
    # cu values are traced (uninspectable), so require the same object.
    fast_ok = dropout == 0.0 and \
        (not causal or cu_seqlens_q is cu_seqlens_k)
    if fast_ok:
        q4 = query[None]
        k4 = key[None]
        v4 = value[None]
        if _use_pallas(q4, k4):
            from ._pallas.flash_attention import flash_attention_pallas

            def token_segments(cu, total, pad_sentinel):
                # token -> sequence index; tail padding (tokens past
                # cu[-1], if the caller padded the packed dim) gets a
                # side-specific sentinel so q-padding and k-padding never
                # match each other -> padded rows attend nothing and come
                # out as the kernel's masked-row zeros
                idx = jnp.arange(total)
                seg = jnp.searchsorted(cu, idx, side="right") - 1
                return jnp.where(idx < cu[-1], seg, pad_sentinel)

            # q and k carry their own boundaries: cross-attention packings
            # with different per-sequence splits stay correct
            seg_q = token_segments(cu_seqlens_q, total_q, -1)
            seg_k = token_segments(cu_seqlens_k, key.shape[0], -2)
            out = flash_attention_pallas(q4, k4, v4, causal=causal,
                                         scale=scale,
                                         segment_ids=seg_q[None],
                                         segment_ids_k=seg_k[None])
            return out[0]
    # Scatter the packed tokens into [B, max_seqlen, H, D].
    def to_padded(x, cu, max_len):
        out = jnp.zeros((b, max_len, x.shape[-2], x.shape[-1]), x.dtype)
        idx = jnp.arange(x.shape[0])
        seg = jnp.searchsorted(cu, idx, side="right") - 1
        pos = idx - cu[seg]
        return out.at[seg, pos].set(x)

    qp = to_padded(query, cu_seqlens_q, max_seqlen_q)
    kp = to_padded(key, cu_seqlens_k, max_seqlen_k)
    vp = to_padded(value, cu_seqlens_k, max_seqlen_k)
    lens_q = cu_seqlens_q[1:] - cu_seqlens_q[:-1]
    lens_k = cu_seqlens_k[1:] - cu_seqlens_k[:-1]
    qmask = jnp.arange(max_seqlen_q)[None, :] < lens_q[:, None]
    kmask = jnp.arange(max_seqlen_k)[None, :] < lens_k[:, None]
    bias = jnp.where(kmask[:, None, None, :], 0.0, -jnp.inf)
    out = reference_attention(qp, kp, vp, causal=causal, scale=scale, bias=bias)
    out = jnp.where(qmask[:, :, None, None], out, 0.0)
    # Pack back.
    idx = jnp.arange(total_q)
    seg = jnp.searchsorted(cu_seqlens_q, idx, side="right") - 1
    pos = idx - cu_seqlens_q[seg]
    return out[seg, pos]
