"""Fused 1x1-conv (matmul) + BatchNorm-apply + ReLU + stats Pallas kernels.

ResNet-style conv nets on TPU are HBM-bandwidth-bound, not MXU-bound (the
round-3 profile: every fusion at 620-700 GB/s, OI 1-30). The dominant
avoidable traffic is the *separate* BN-normalize/ReLU pass between convs:
XLA materializes relu(x*scale+shift) before each conv reads it. A 1x1 conv
is a plain matmul over [N*H*W, C], so the whole chain

    y = relu(x * scale + shift) @ W          (+ per-channel sum/sumsq of y)

fuses into ONE kernel that reads x once and writes y once — the normalize
pass (one full read + one full write of the activation) disappears, and the
next BN's stats come out of the epilogue for free. The backward kernels
recompute the prologue from x instead of loading saved intermediates
(flash-attention-style rematerialization inside the kernel).

Reference parity: the conv+BN(+ReLU) fusion passes of
``paddle/fluid/framework/ir/conv_bn_fuse_pass.cc`` (inference) and the
cuDNN fused conv-BN-activation kernels the reference dispatches to — here
re-designed TPU-first as an HBM-traffic optimization for training.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_matmul_bn_act"]


def _fwd_kernel(x_ref, w_ref, scale_ref, shift_ref, y_ref, s_ref, ss_ref,
                s_scr, ss_scr, *, prologue: str, stats: bool, nm: int):
    i = pl.program_id(1)  # row-block index (inner grid axis)
    xb = x_ref[0]
    if prologue != "none":
        xb = xb * scale_ref[0].astype(xb.dtype) + \
            shift_ref[0].astype(xb.dtype)
        if prologue == "scale_shift_relu":
            xb = jnp.maximum(xb, 0)
    acc = jax.lax.dot_general(xb, w_ref[0], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    y_ref[0] = acc.astype(y_ref.dtype)
    if stats:
        @pl.when(i == 0)
        def _init():
            s_scr[...] = jnp.zeros_like(s_scr)
            ss_scr[...] = jnp.zeros_like(ss_scr)

        s_scr[...] += jnp.sum(acc, axis=0, keepdims=True)
        ss_scr[...] += jnp.sum(acc * acc, axis=0, keepdims=True)

        @pl.when(i == nm - 1)
        def _fin():
            s_ref[0] = s_scr[...]
            ss_ref[0] = ss_scr[...]


def _fwd(x, w, scale, shift, prologue: str, stats: bool, block_m: int):
    m, cin = x.shape
    cout = w.shape[1]
    block_m = min(block_m, m)
    nm = m // block_m
    grid = (1, nm)  # trivial outer axis keeps the row loop innermost
    kern = functools.partial(_fwd_kernel, prologue=prologue, stats=stats,
                             nm=nm)
    out_shape = [
        jax.ShapeDtypeStruct((m, cout), x.dtype),
        jax.ShapeDtypeStruct((1, cout), jnp.float32),
        jax.ShapeDtypeStruct((1, cout), jnp.float32),
    ]
    y, s, ss = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_m, cin), lambda j, i: (0, i, 0)),
            pl.BlockSpec((1, cin, cout), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cin), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cin), lambda j, i: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_m, cout), lambda j, i: (0, i, 0)),
            pl.BlockSpec((1, 1, cout), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cout), lambda j, i: (0, 0, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((1,) + o.shape, o.dtype)
                   for o in out_shape],
        scratch_shapes=[
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * m * cin * cout,
            bytes_accessed=x.size * x.dtype.itemsize +
            y_bytes(m, cout, x.dtype) + w.size * w.dtype.itemsize,
            transcendentals=0,
        ),
    )(x[None], w[None], scale[None, None].astype(jnp.float32),
      shift[None, None].astype(jnp.float32))
    return y[0], s[0, 0], ss[0, 0]


def y_bytes(m, cout, dtype):
    return m * cout * jnp.dtype(dtype).itemsize


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_matmul_bn_act(x, w, scale, shift, prologue: str = "scale_shift_relu",
                        stats: bool = True, block_m: int = 512):
    """relu(x*scale+shift) @ w with per-channel output stats, one HBM pass.

    x: [M, Cin] (bf16), w: [Cin, Cout], scale/shift: [Cin] f32.
    Returns (y [M, Cout], sum [Cout] f32, sumsq [Cout] f32).
    prologue: 'none' | 'scale_shift' | 'scale_shift_relu'.
    """
    return _fwd(x, w, scale, shift, prologue, stats, block_m)


def _vjp_fwd(x, w, scale, shift, prologue, stats, block_m):
    out = _fwd(x, w, scale, shift, prologue, stats, block_m)
    return out, (x, w, scale, shift)


def _vjp_bwd(prologue, stats, block_m, res, cts):
    x, w, scale, shift = res
    dy, ds, dss = cts
    # Stats cotangents fold into dy: d/dy (s·ds + ss·dss) = ds + 2 y dss.
    # y is recomputed... avoided: express via the same fused matmul — the
    # dss term needs y, so recompute y only when dss is nonzero is not
    # knowable here; instead compute the effective dy in one elementwise
    # pass (y comes back via a second fused matmul when needed).
    needs_y = dss is not None
    xb = x
    if prologue != "none":
        xb = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        if prologue == "scale_shift_relu":
            xb = jnp.maximum(xb, 0)
    if stats and (ds is not None or dss is not None):
        y = xb @ w  # recompute (bwd only runs when stats grads flow)
        dy = dy.astype(jnp.float32) + ds[None, :] + \
            2.0 * y.astype(jnp.float32) * dss[None, :]
        dy = dy.astype(x.dtype)
    da = (dy @ w.T.astype(dy.dtype))
    dw = (xb.T @ dy).astype(w.dtype)
    if prologue == "none":
        return da.astype(x.dtype), dw, None, None
    if prologue == "scale_shift_relu":
        da = da * (xb > 0)
    daf = da.astype(jnp.float32)
    dscale = jnp.sum(daf * x.astype(jnp.float32), axis=0)
    dshift = jnp.sum(daf, axis=0)
    dx = (da * scale.astype(da.dtype)).astype(x.dtype)
    return dx, dw, dscale, dshift


fused_matmul_bn_act.defvjp(_vjp_fwd, _vjp_bwd)
