"""Head-packed flash attention for small head dims (d=64) on TPU.

At d=64 every q/k/v tile is 64 lanes wide — half of the 128-lane VREG/MXU
width — and at encoder shapes (S=512, d=64) the per-program MXU work is a
few microseconds, so the plain per-head grid (one program per (batch,
head, q-block, k-block)) is dominated by program-dispatch and half-lane
DMA overhead, not FLOPs. This kernel packs G heads per program on the
LANE axis: arrays are laid out [B*H/G, S, G*64] (a pure reshape — head
features are already lane-contiguous in [B, S, H, 64]), the grid shrinks
by G, every DMA moves full 128-lane tiles, and the per-head dots are
static lane slices of the packed tile. The MXU pass count is unchanged
(a [bq,64]x[64,bk] dot costs the same passes as [bq,128]x[128,bk] — the
contraction is padded to the 128-deep systolic array either way; that
halved FLOP rate is the architectural floor for d=64 and no packing
scheme beats it), so all the win is dispatch + bandwidth + layout, which
is exactly what dominates at these shapes.

Reference parity: ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:324``
serves all head dims at full tensor-core rate (16-deep MACs); this is the
TPU-shaped answer to the same requirement. Dropout positions hash
identically to ``flash_attention.dropout_keep_dense`` (flat query-head
index b*H + h), so packed, unpacked, and dense-mirror paths agree bit-
for-bit under a fixed seed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (NEG_INF, _causal_mask, _dot, _dropout_keepf)

__all__ = ["flash_attention_packed", "pack_group"]

HEAD_D = 64  # the packed path exists for exactly this head dim
MAX_PACK_LANES = 1024  # cap G*64 so tiles stay comfortably in VMEM


def pack_group(num_heads: int) -> int:
    """Largest even divisor of num_heads whose packed width fits the lane
    cap (even keeps every slice 128-aligned at least every other head)."""
    best = 0
    for g in range(2, num_heads + 1, 2):
        if num_heads % g == 0 and g * HEAD_D <= MAX_PACK_LANES:
            best = g
    return best


def _pick_blocks_packed(sq: int, sk: int, dp: int, bwd: bool = False):
    """(block_q, block_k) for the packed tile width dp = G*64. The G-way
    unrolled head loop keeps several [bq, bk] f32 temporaries live, and
    Mosaic's scoped-vmem stack is 16 MB — the backward kernels (5 live
    temporaries per head vs the forward's 2) need smaller score tiles, so
    bwd caps at 256-square. The autotune cache overrides when populated
    (key class flash_packed / flash_packed_bwd)."""
    try:
        from .autotune import get_cache
        hit = get_cache().get("flash_packed" + ("_bwd" if bwd else ""),
                              f"sq{sq}_sk{sk}_dp{dp}")
        if hit:
            tq, tk = tuple(hit)
            return min(tq, sq), min(tk, sk)
    except Exception:
        pass
    # on-chip sweep at B64 S512 H12, fwd+bwd, device time, only configs
    # that pass the numeric guard: bwd 256x512 5.20 ms vs 256x256 5.91 /
    # 512x256 6.05; 512x512 overflows the 16MB scoped-vmem stack (the
    # G-way unrolled head loop keeps ~5 [bq,bk] f32 temporaries live).
    if bwd:
        cq, ck = (256, 512) if dp <= 768 else (128, 256)
    else:
        # 512-square q tiles overflow the stack in the G=12 direct form
        # (in-graph, with the segment/bias dummies); 256x512 fits and
        # keeps block_k == seq for the scratch-free single-k-block path.
        cq, ck = (256, 512) if dp <= 768 else (256, 256)

    def fit(cap, s):
        b = min(cap, s)
        while b > 128 and s % b:
            b -= 128
        return b

    return fit(cq, sq), fit(ck, sk)


def _seg_mask_b(s, segq_ref, segk_ref):
    seg_q = segq_ref[0].T        # [bq, 1]
    seg_k = segk_ref[0]          # [1, bk]
    return jnp.where(seg_q == seg_k, s, NEG_INF)


def _flat_head(bg, hg, g_pack, h, num_heads):
    """Flat query-head row (b*H + head) for the dropout hash: packed row
    bg = b*HG + g holds original heads g*G .. g*G+G-1."""
    return (bg // hg) * num_heads + (bg % hg) * g_pack + h


def _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, seed_ref, bias_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, segmented, block_q, block_k, seq_q, seq_k,
                g_pack, hg, num_heads, dropout=0.0, biased=False):
    bg = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    offset = seq_k - seq_q

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    in_band = jnp.asarray(True) if not causal \
        else kj * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(in_band)
    def _step():
        qp = q_ref[0]            # [bq, G*64]
        kp = k_ref[0]            # [bk, G*64]
        vp = v_ref[0]
        for h in range(g_pack):
            sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
            s = _dot(qp[:, sl], kp[:, sl], ((1,), (1,))) * scale
            if causal:
                s = _causal_mask(s, qi, kj, block_q, block_k, offset)
            if segmented:
                s = _seg_mask_b(s, segq_ref, segk_ref)
            if biased:
                s = s + bias_ref[0]
            hsl = slice(h, h + 1)
            m_prev = m_scr[:, hsl]
            l_prev = l_scr[:, hsl]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
            alpha = jnp.exp(m_prev - m_new)
            m_scr[:, hsl] = m_new
            l_scr[:, hsl] = l_prev * alpha + jnp.sum(p, axis=1,
                                                     keepdims=True)
            pv = p
            if dropout > 0.0:
                pv = p * _dropout_keepf(
                    p.shape, _flat_head(bg, hg, g_pack, h, num_heads),
                    qi, kj, block_q, block_k, seq_q, seq_k,
                    seed_ref[0], dropout)
            acc_scr[:, sl] = acc_scr[:, sl] * alpha \
                + _dot(pv.astype(vp.dtype), vp[:, sl], ((1,), (0,)))

    @pl.when(kj == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[...], 1e-30)          # [bq, G]
        # acc is [bq, G*64]; divide each head's 64 lanes by its l column
        # (per-head slice stores — Mosaic has no [bq,G]->[bq,G*64] repeat)
        for h in range(g_pack):
            sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
            o_ref[0, :, sl] = (acc_scr[:, sl]
                               / l[:, h:h + 1]).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l)        # [bq, G]


def _fwd_kernel_direct(q_ref, k_ref, v_ref, segq_ref, segk_ref, seed_ref,
                       bias_ref, o_ref, lse_ref,
                       *, scale, causal, segmented, block_q, block_k,
                       seq_q, seq_k, g_pack, hg, num_heads, dropout=0.0,
                       biased=False):
    """Single-k-block specialization (block_k >= seq_k): plain per-head
    softmax, no online-max scratch, no narrow-lane m/l read-modify-write —
    measured 2.2x faster than the streamed form at B64 S512 G12 (the
    common encoder shape puts the WHOLE key sequence in one tile)."""
    bg = pl.program_id(0)
    qi = pl.program_id(1)
    offset = seq_k - seq_q
    qp = q_ref[0]
    kp = k_ref[0]
    vp = v_ref[0]
    for h in range(g_pack):
        sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
        s = _dot(qp[:, sl], kp[:, sl], ((1,), (1,))) * scale
        if causal:
            s = _causal_mask(s, qi, 0, block_q, block_k, offset)
        if segmented:
            s = _seg_mask_b(s, segq_ref, segk_ref)
        if biased:
            s = s + bias_ref[0]
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m) * (s > NEG_INF / 2)
        l = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
        pv = p
        if dropout > 0.0:
            pv = p * _dropout_keepf(
                p.shape, _flat_head(bg, hg, g_pack, h, num_heads), qi, 0,
                block_q, block_k, seq_q, seq_k, seed_ref[0], dropout)
        o = _dot(pv.astype(vp.dtype), vp[:, sl], ((1,), (0,)))
        o_ref[0, :, sl] = (o / l).astype(o_ref.dtype)
        lse_ref[0, :, h:h + 1] = m + jnp.log(l)


def _fwd(q, k, v, scale, causal, block_q, block_k, g_pack, num_heads,
         seg_q=None, seg_k=None, dropout=0.0, seed=None, bias=None):
    """q/k/v: [B*HG, S, G*64] packed; seg_q/k: [B, 1, S] int32 or None;
    bias: [B, 1, Sk] f32 or None -> (o, lse [B*HG, G, Sq] f32)."""
    bhg, sq, dp = q.shape
    sk = k.shape[1]
    hg = num_heads // g_pack
    b = bhg // hg
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    segmented = seg_q is not None
    if not segmented:
        seg_q = jnp.zeros((b, 1, sq), jnp.int32)
        seg_k = jnp.zeros((b, 1, sk), jnp.int32)
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    biased = bias is not None
    if not biased:
        bias = jnp.zeros((b, 1, sk), jnp.float32)
    nq, nk = sq // block_q, sk // block_k
    cost = pl.CostEstimate(
        flops=4 * bhg * g_pack * sq * sk * HEAD_D
        // (2 if causal else 1),
        bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
        transcendentals=bhg * g_pack * sq * sk,
    )
    out_shape = [
        jax.ShapeDtypeStruct((bhg, sq, dp), q.dtype),
        jax.ShapeDtypeStruct((bhg, sq, g_pack), jnp.float32),
    ]
    if nk == 1:
        kern = functools.partial(
            _fwd_kernel_direct, scale=scale, causal=causal,
            segmented=segmented, block_q=block_q, block_k=block_k,
            seq_q=sq, seq_k=sk, g_pack=g_pack, hg=hg,
            num_heads=num_heads, dropout=dropout, biased=biased)
        o, lse = pl.pallas_call(
            kern,
            grid=(bhg, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, dp), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_k, dp), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, block_k, dp), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, 1, block_q),
                             lambda b_, i, _hg=hg: (b_ // _hg, 0, i)),
                pl.BlockSpec((1, 1, block_k),
                             lambda b_, i, _hg=hg: (b_ // _hg, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, block_k),
                             lambda b_, i, _hg=hg: (b_ // _hg, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, dp), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_q, g_pack),
                             lambda b_, i: (b_, i, 0)),
            ],
            out_shape=out_shape,
            cost_estimate=cost,
        )(q, k, v, seg_q, seg_k, seed, bias)
        return o, lse
    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, segmented=segmented,
        block_q=block_q, block_k=block_k, seq_q=sq, seq_k=sk,
        g_pack=g_pack, hg=hg, num_heads=num_heads, dropout=dropout,
        biased=biased)
    o, lse = pl.pallas_call(
        kern,
        grid=(bhg, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b_, i, j, _hg=hg: (b_ // _hg, 0, i)),
            pl.BlockSpec((1, 1, block_k),
                         lambda b_, i, j, _hg=hg: (b_ // _hg, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_k),
                         lambda b_, i, j, _hg=hg: (b_ // _hg, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_q, g_pack), lambda b_, i, j: (b_, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, g_pack), jnp.float32),
            pltpu.VMEM((block_q, g_pack), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ],
        cost_estimate=cost,
    )(q, k, v, seg_q, seg_k, seed, bias)
    return o, lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   segq_ref, segk_ref, seed_ref, bias_ref, dq_ref, dq_scr,
                   *, scale, causal, segmented, block_q, block_k,
                   seq_q, seq_k, g_pack, hg, num_heads, dropout=0.0,
                   biased=False):
    bg = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    offset = seq_k - seq_q

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    in_band = jnp.asarray(True) if not causal \
        else kj * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(in_band)
    def _step():
        qp = q_ref[0]
        kp = k_ref[0]
        vp = v_ref[0]
        dop = do_ref[0]
        for h in range(g_pack):
            sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
            lse = lse_ref[0][:, h:h + 1]        # [bq, 1]
            delta = delta_ref[0][:, h:h + 1]
            s = _dot(qp[:, sl], kp[:, sl], ((1,), (1,))) * scale
            if causal:
                s = _causal_mask(s, qi, kj, block_q, block_k, offset)
            if segmented:
                s = _seg_mask_b(s, segq_ref, segk_ref)
            if biased:
                s = s + bias_ref[0]
            p = jnp.exp(s - lse) * (s > NEG_INF / 2)
            dp = _dot(dop[:, sl], vp[:, sl], ((1,), (1,)))
            if dropout > 0.0:
                dp = dp * _dropout_keepf(
                    p.shape, _flat_head(bg, hg, g_pack, h, num_heads),
                    qi, kj, block_q, block_k, seq_q, seq_k,
                    seed_ref[0], dropout)
            ds = (p * (dp - delta) * scale).astype(kp.dtype)
            dq_scr[:, sl] = dq_scr[:, sl] + _dot(ds, kp[:, sl],
                                                 ((1,), (0,)))

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    segq_ref, segk_ref, seed_ref, bias_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr,
                    *, scale, causal, segmented, block_q, block_k,
                    seq_q, seq_k, g_pack, hg, num_heads, dropout=0.0,
                    biased=False):
    bg = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    offset = seq_k - seq_q

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    in_band = jnp.asarray(True) if not causal \
        else (qi + 1) * block_q - 1 + offset >= kj * block_k

    @pl.when(in_band)
    def _step():
        kp = k_ref[0]
        vp = v_ref[0]
        qp = q_ref[0]
        dop = do_ref[0]
        for h in range(g_pack):
            sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
            lse = lse_ref[0][:, h:h + 1]
            delta = delta_ref[0][:, h:h + 1]
            s = _dot(qp[:, sl], kp[:, sl], ((1,), (1,))) * scale
            if causal:
                s = _causal_mask(s, qi, kj, block_q, block_k, offset)
            if segmented:
                s = _seg_mask_b(s, segq_ref, segk_ref)
            if biased:
                s = s + bias_ref[0]
            p = jnp.exp(s - lse) * (s > NEG_INF / 2)
            pv = p
            dp = _dot(dop[:, sl], vp[:, sl], ((1,), (1,)))
            if dropout > 0.0:
                keepf = _dropout_keepf(
                    p.shape, _flat_head(bg, hg, g_pack, h, num_heads),
                    qi, kj, block_q, block_k, seq_q, seq_k,
                    seed_ref[0], dropout)
                pv = p * keepf
                dp = dp * keepf
            dv_scr[:, sl] = dv_scr[:, sl] + _dot(
                pv.astype(dop.dtype), dop[:, sl], ((0,), (0,)))
            ds = (p * (dp - delta) * scale).astype(qp.dtype)
            dk_scr[:, sl] = dk_scr[:, sl] + _dot(ds, qp[:, sl],
                                                 ((0,), (0,)))

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dkv_kernel_direct(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                           segq_ref, segk_ref, seed_ref, bias_ref, dk_ref,
                           dv_ref,
                           *, scale, causal, segmented, block_q, block_k,
                           seq_q, seq_k, g_pack, hg, num_heads,
                           dropout=0.0, biased=False):
    """Single-q-block dk/dv: the whole query sequence sits in one tile."""
    bg = pl.program_id(0)
    kj = pl.program_id(1)
    offset = seq_k - seq_q
    kp = k_ref[0]
    vp = v_ref[0]
    qp = q_ref[0]
    dop = do_ref[0]
    for h in range(g_pack):
        sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
        lse = lse_ref[0][:, h:h + 1]
        delta = delta_ref[0][:, h:h + 1]
        s = _dot(qp[:, sl], kp[:, sl], ((1,), (1,))) * scale
        if causal:
            s = _causal_mask(s, 0, kj, block_q, block_k, offset)
        if segmented:
            s = _seg_mask_b(s, segq_ref, segk_ref)
        if biased:
            s = s + bias_ref[0]
        p = jnp.exp(s - lse) * (s > NEG_INF / 2)
        pv = p
        dp = _dot(dop[:, sl], vp[:, sl], ((1,), (1,)))
        if dropout > 0.0:
            keepf = _dropout_keepf(
                p.shape, _flat_head(bg, hg, g_pack, h, num_heads), 0, kj,
                block_q, block_k, seq_q, seq_k, seed_ref[0], dropout)
            pv = p * keepf
            dp = dp * keepf
        dv_ref[0, :, sl] = _dot(pv.astype(dop.dtype), dop[:, sl],
                                ((0,), (0,))).astype(dv_ref.dtype)
        ds = (p * (dp - delta) * scale).astype(qp.dtype)
        dk_ref[0, :, sl] = _dot(ds, qp[:, sl],
                                ((0,), (0,))).astype(dk_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      segq_ref, segk_ref, seed_ref, bias_ref,
                      dq_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                      *, scale, causal, segmented, block_q, block_k,
                      seq_q, seq_k, g_pack, hg, num_heads, dropout=0.0,
                      biased=False):
    """Fused dq+dkv for the single-k-block regime (block_k >= seq_k).

    The r4 fused-backward attempt was rejected because dq and dk/dv have
    conflicting reduction axes — accumulating one of them meant HBM
    read-modify-write across grid steps, unsound under Mosaic's async
    output windows. With the WHOLE key sequence in the tile that conflict
    disappears: dq is complete within one program (its k-reduction is the
    in-tile dot), and dk/dv accumulate across the streamed q-blocks in
    VMEM scratch — the one (s, p) recompute serves all three gradients
    (5 dot-sets per head vs 3+4 in the split kernels, exp once vs twice,
    q/do DMA'd once vs twice)."""
    bg = pl.program_id(0)
    qi = pl.program_id(1)
    nq = pl.num_programs(1)
    offset = seq_k - seq_q

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    qp = q_ref[0]
    kp = k_ref[0]
    vp = v_ref[0]
    dop = do_ref[0]
    for h in range(g_pack):
        sl = slice(h * HEAD_D, (h + 1) * HEAD_D)
        lse = lse_ref[0][:, h:h + 1]
        delta = delta_ref[0][:, h:h + 1]
        s = _dot(qp[:, sl], kp[:, sl], ((1,), (1,))) * scale
        if causal:
            s = _causal_mask(s, qi, 0, block_q, block_k, offset)
        if segmented:
            s = _seg_mask_b(s, segq_ref, segk_ref)
        if biased:
            s = s + bias_ref[0]
        p = jnp.exp(s - lse) * (s > NEG_INF / 2)
        pv = p
        dp = _dot(dop[:, sl], vp[:, sl], ((1,), (1,)))
        if dropout > 0.0:
            keepf = _dropout_keepf(
                p.shape, _flat_head(bg, hg, g_pack, h, num_heads), qi, 0,
                block_q, block_k, seq_q, seq_k, seed_ref[0], dropout)
            pv = p * keepf
            dp = dp * keepf
        ds = (p * (dp - delta) * scale).astype(kp.dtype)
        dq_ref[0, :, sl] = _dot(ds, kp[:, sl],
                                ((1,), (0,))).astype(dq_ref.dtype)
        dv_scr[:, sl] = dv_scr[:, sl] + _dot(
            pv.astype(dop.dtype), dop[:, sl], ((0,), (0,)))
        dk_scr[:, sl] = dk_scr[:, sl] + _dot(ds, qp[:, sl], ((0,), (0,)))

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k, g_pack,
         num_heads, seg_q=None, seg_k=None, dropout=0.0, seed=None,
         bias=None):
    bhg, sq, dp = q.shape
    sk = k.shape[1]
    hg = num_heads // g_pack
    b = bhg // hg
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    segmented = seg_q is not None
    if not segmented:
        seg_q = jnp.zeros((b, 1, sq), jnp.int32)
        seg_k = jnp.zeros((b, 1, sk), jnp.int32)
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    biased = bias is not None
    if not biased:
        bias = jnp.zeros((b, 1, sk), jnp.float32)
    # per-head delta = rowsum(dO * O): [B*HG, Sq, G] matching the lse layout
    prod = (do.astype(jnp.float32) * o.astype(jnp.float32))
    delta = prod.reshape(bhg, sq, g_pack, HEAD_D).sum(-1)
    nq, nk = sq // block_q, sk // block_k

    def batch_of(b_, i, j, _hg=hg):
        return b_ // _hg

    kw = dict(scale=scale, causal=causal, segmented=segmented,
              seq_q=sq, seq_k=sk, g_pack=g_pack, hg=hg,
              num_heads=num_heads, dropout=dropout, biased=biased)

    if nk == 1:
        # fused dq+dkv: one (s, p) recompute serves all three grads
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, block_q=block_q,
                              block_k=block_k, **kw),
            grid=(bhg, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, dp), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_k, dp), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, block_k, dp), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, block_q, dp), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_q, g_pack),
                             lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_q, g_pack),
                             lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, 1, block_q),
                             lambda b_, i, _hg=hg: (b_ // _hg, 0, i)),
                pl.BlockSpec((1, 1, block_k),
                             lambda b_, i, _hg=hg: (b_ // _hg, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, block_k),
                             lambda b_, i, _hg=hg: (b_ // _hg, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, dp), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_k, dp), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, block_k, dp), lambda b_, i: (b_, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bhg, sq, dp), q.dtype),
                jax.ShapeDtypeStruct((bhg, sk, dp), k.dtype),
                jax.ShapeDtypeStruct((bhg, sk, dp), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, dp), jnp.float32),
                pltpu.VMEM((block_k, dp), jnp.float32),
            ],
        )(q, k, v, do, lse, delta, seg_q, seg_k, seed, bias)
        return dq, dk, dv
    if nk > 1:  # streamed dq over key blocks
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, block_q=block_q,
                              block_k=block_k, **kw),
            grid=(bhg, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, dp),
                             lambda b_, i, j: (b_, i, 0)),
                pl.BlockSpec((1, block_k, dp),
                             lambda b_, i, j: (b_, j, 0)),
                pl.BlockSpec((1, block_k, dp),
                             lambda b_, i, j: (b_, j, 0)),
                pl.BlockSpec((1, block_q, dp),
                             lambda b_, i, j: (b_, i, 0)),
                pl.BlockSpec((1, block_q, g_pack),
                             lambda b_, i, j: (b_, i, 0)),
                pl.BlockSpec((1, block_q, g_pack),
                             lambda b_, i, j: (b_, i, 0)),
                pl.BlockSpec((1, 1, block_q),
                             lambda b_, i, j: (batch_of(b_, i, j), 0, i)),
                pl.BlockSpec((1, 1, block_k),
                             lambda b_, i, j: (batch_of(b_, i, j), 0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, block_k),
                             lambda b_, i, j: (batch_of(b_, i, j), 0, j)),
            ],
            out_specs=pl.BlockSpec((1, block_q, dp),
                                   lambda b_, i, j: (b_, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bhg, sq, dp), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        )(q, k, v, do, lse, delta, seg_q, seg_k, seed, bias)

    # dkv mirrors the dq tiling: its streamed axis is q, so it gets the
    # SMALL tile on q and the large one on k (block_k x block_q swapped);
    # unmirrored when sq != sk makes the swap non-dividing.
    kq, kk = block_k, block_q
    if sq % min(kq, sq) or sk % min(kk, sk):
        kq, kk = block_q, block_k
    nkv_q, nkv_k = sq // min(kq, sq), sk // min(kk, sk)
    kq, kk = min(kq, sq), min(kk, sk)
    dkv_out = [
        jax.ShapeDtypeStruct((bhg, sk, dp), k.dtype),
        jax.ShapeDtypeStruct((bhg, sk, dp), v.dtype),
    ]
    if nkv_q == 1:
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel_direct, block_q=kq,
                              block_k=kk, **kw),
            grid=(bhg, nkv_k),
            in_specs=[
                pl.BlockSpec((1, kk, dp), lambda b_, j: (b_, j, 0)),
                pl.BlockSpec((1, kk, dp), lambda b_, j: (b_, j, 0)),
                pl.BlockSpec((1, kq, dp), lambda b_, j: (b_, 0, 0)),
                pl.BlockSpec((1, kq, dp), lambda b_, j: (b_, 0, 0)),
                pl.BlockSpec((1, kq, g_pack), lambda b_, j: (b_, 0, 0)),
                pl.BlockSpec((1, kq, g_pack), lambda b_, j: (b_, 0, 0)),
                pl.BlockSpec((1, 1, kq),
                             lambda b_, j, _hg=hg: (b_ // _hg, 0, 0)),
                pl.BlockSpec((1, 1, kk),
                             lambda b_, j, _hg=hg: (b_ // _hg, 0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, kk),
                             lambda b_, j, _hg=hg: (b_ // _hg, 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, kk, dp), lambda b_, j: (b_, j, 0)),
                pl.BlockSpec((1, kk, dp), lambda b_, j: (b_, j, 0)),
            ],
            out_shape=dkv_out,
        )(k, v, q, do, lse, delta, seg_q, seg_k, seed, bias)
    else:
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, block_q=kq, block_k=kk,
                              **kw),
            grid=(bhg, nkv_k, nkv_q),
            in_specs=[
                pl.BlockSpec((1, kk, dp), lambda b_, j, t: (b_, j, 0)),
                pl.BlockSpec((1, kk, dp), lambda b_, j, t: (b_, j, 0)),
                pl.BlockSpec((1, kq, dp), lambda b_, j, t: (b_, t, 0)),
                pl.BlockSpec((1, kq, dp), lambda b_, j, t: (b_, t, 0)),
                pl.BlockSpec((1, kq, g_pack), lambda b_, j, t: (b_, t, 0)),
                pl.BlockSpec((1, kq, g_pack), lambda b_, j, t: (b_, t, 0)),
                pl.BlockSpec((1, 1, kq),
                             lambda b_, j, t: (batch_of(b_, j, t), 0, t)),
                pl.BlockSpec((1, 1, kk),
                             lambda b_, j, t: (batch_of(b_, j, t), 0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, kk),
                             lambda b_, j, t: (batch_of(b_, j, t), 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, kk, dp), lambda b_, j, t: (b_, j, 0)),
                pl.BlockSpec((1, kk, dp), lambda b_, j, t: (b_, j, 0)),
            ],
            out_shape=dkv_out,
            scratch_shapes=[
                pltpu.VMEM((kk, dp), jnp.float32),
                pltpu.VMEM((kk, dp), jnp.float32),
            ],
        )(k, v, q, do, lse, delta, seg_q, seg_k, seed, bias)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(7, 8, 9, 10, 11, 12, 13, 14, 15))
def _flash_packed(q, k, v, seg_q, seg_k, seed, bias, scale, causal,
                  block_q, block_k, bwd_bq, bwd_bk, g_pack, num_heads,
                  dropout):
    o, _ = _fwd(q, k, v, scale, causal, block_q, block_k, g_pack, num_heads,
                seg_q, seg_k, dropout=dropout, seed=seed, bias=bias)
    return o


def _flash_packed_fwd(q, k, v, seg_q, seg_k, seed, bias, scale, causal,
                      block_q, block_k, bwd_bq, bwd_bk, g_pack, num_heads,
                      dropout):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k, g_pack,
                  num_heads, seg_q, seg_k, dropout=dropout, seed=seed,
                  bias=bias)
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse, seg_q, seg_k, seed, bias)


def _flash_packed_bwd(scale, causal, block_q, block_k, bwd_bq, bwd_bk,
                      g_pack, num_heads, dropout, res, do):
    q, k, v, o, lse, seg_q, seg_k, seed, bias = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale, causal, bwd_bq, bwd_bk,
                      g_pack, num_heads, seg_q, seg_k, dropout=dropout,
                      seed=seed, bias=bias)
    return dq, dk, dv, None, None, None, None


_flash_packed.defvjp(_flash_packed_fwd, _flash_packed_bwd)


def flash_attention_packed(query, key, value, causal=False, scale=None,
                           block_q=None, block_k=None, segment_ids=None,
                           segment_ids_k=None, dropout=0.0,
                           dropout_seed=None, key_bias=None,
                           g_pack=None):
    """[B, S, H, 64] flash attention with G heads packed per program.

    Drop-in equal to ``flash_attention_pallas`` for d=64 dense-head (MHA)
    shapes — same math, same dropout hash, same lse semantics — routed by
    the caller when the packing preconditions hold (d == 64, kv heads ==
    query heads, H divisible by an even group)."""
    import math as _math
    b, sq, h, d = query.shape
    if d != HEAD_D:
        raise ValueError(f"packed path is d=64 only; got {d}")
    sk = key.shape[1]
    if key.shape[2] != h:
        raise ValueError("packed path needs kv heads == query heads")
    g = g_pack or pack_group(h)
    if not g:
        raise ValueError(f"no even pack group divides {h} heads")
    hg = h // g
    auto_q, auto_k = _pick_blocks_packed(sq, sk, d * g)
    bwd_auto_q, bwd_auto_k = _pick_blocks_packed(sq, sk, d * g, bwd=True)
    # explicit caller blocks pin BOTH directions (sweep/test hook)
    bwd_bq = block_q or bwd_auto_q
    bwd_bk = block_k or bwd_auto_k
    block_q = block_q or auto_q
    block_k = block_k or auto_k
    if sq % min(block_q, sq) or sk % min(block_k, sk):
        raise ValueError(
            f"packed flash needs seq lengths divisible by blocks; "
            f"sq={sq}, sk={sk}")
    from ...core import flags as _flags
    if _flags.flag("static_analysis") != "off":
        # Enforce the tuning folklore statically (P001/P004: the backward
        # score-tile VMEM budget that forced the 256-row cap) before
        # Mosaic hits it at compile time on hardware.
        from ...analysis import pallas_check as _pc
        _pc.enforce(_pc.spec_for_flash_packed(
            sq, sk, g * HEAD_D, block_q, block_k, g, query.dtype),
            where="flash_attention_packed")
        _pc.enforce(_pc.spec_for_flash_packed(
            sq, sk, g * HEAD_D, bwd_bq, bwd_bk, g, query.dtype, bwd=True),
            where="flash_attention_packed")
    scale = scale if scale is not None else 1.0 / _math.sqrt(d)

    def to_packed(x, s):
        # [B, S, H, 64] -> [B, S, HG, G*64] is a pure reshape (head
        # features are lane-contiguous); then one full-lane transpose.
        return (x.reshape(b, s, hg, g * HEAD_D)
                 .transpose(0, 2, 1, 3)
                 .reshape(b * hg, s, g * HEAD_D))

    q = to_packed(query, sq)
    k = to_packed(key, sk)
    v = to_packed(value, sk)
    seg_q = seg_k = None
    if segment_ids is not None:
        def as_seg(ids, s_, what):
            from ...analysis._jaxpr_utils import fmt_shape
            ids = jnp.asarray(ids, jnp.int32)
            if ids.shape != (b, s_):
                raise ValueError(
                    f"{what} must be [batch, seq] = {fmt_shape((b, s_))}; "
                    f"got {fmt_shape(ids.shape)}")
            return ids.reshape(b, 1, s_)
        seg_q = as_seg(segment_ids, sq, "segment_ids")
        sk_ids = segment_ids_k if segment_ids_k is not None else \
            (segment_ids if sq == sk else None)
        if sk_ids is None:
            raise ValueError("segment_ids_k required when sq != sk")
        seg_k = as_seg(sk_ids, sk, "segment_ids_k")
    if dropout > 0.0:
        if dropout_seed is None:
            from ...core.random import next_key
            dropout_seed = jax.random.randint(
                next_key(), (1,), 0, 2 ** 31 - 1, dtype=jnp.int32)
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    bias = None
    if key_bias is not None:
        bias = jnp.asarray(key_bias, jnp.float32).reshape(b, 1, sk)
    o = _flash_packed(q, k, v, seg_q, seg_k, seed, bias, float(scale),
                      bool(causal), block_q, block_k, bwd_bq, bwd_bk, g, h,
                      float(dropout))
    return (o.reshape(b, hg, sq, g * HEAD_D)
             .transpose(0, 2, 1, 3)
             .reshape(b, sq, h, d))
