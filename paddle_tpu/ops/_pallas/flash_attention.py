"""Flash attention as Pallas TPU kernels (fwd + bwd).

Online-softmax blocked attention (Dao et al.) tiled for the MXU: 128-row
query blocks stream over 128-row key/value blocks held in VMEM, keeping the
full [S, S] score matrix out of HBM. Backward recomputes probabilities from
the saved logsumexp (no O(S^2) residuals), split into a dq kernel (grid over
query blocks) and a dk/dv kernel (grid over key blocks) so each output is
accumulated by exactly one program — no atomics.

Reference parity: ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:324``
(FlashAttnKernel → vendored CUTLASS flash-attn). Layout in/out is paddle's
[batch, seq, heads, head_dim]; internally [batch*heads, seq, head_dim].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_pallas"]

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
LANES = 128  # minor-dim tile width; lse/delta are broadcast across it
NEG_INF = -1e30


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    """Bottom-right-aligned causal mask (flash-attn semantics for sq != sk:
    query i attends keys <= i + sk - sq)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=1)
    return jnp.where(q_pos + offset >= k_pos, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_q, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [bq, d]
    bq, d = q.shape

    num_k = seq_k // block_k
    offset = seq_k - seq_q
    if causal:
        # Only key blocks intersecting the causal band of this query block.
        limit = jax.lax.div((qi + 1) * block_q + offset + block_k - 1,
                            block_k)
        limit = jnp.clip(limit, 0, num_k)
    else:
        limit = num_k

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, j, block_q, block_k, offset)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, limit, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # lse broadcast across the 128-lane minor dim (TPU tiling: the last two
    # block dims must be (8k, 128); same layout as jax's reference kernel).
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape[1:])


def _fwd(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: [BH, S, D] -> (o [BH, Sq, D], lse [BH, Sq] fp32)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (bh, sq // block_q)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k, seq_q=sq,
                             seq_k=sk)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, LANES), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * sq * sk * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * sq * sk // block_k,
        ),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_q, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]        # [bq, 1]
    delta = delta_ref[0][:, 0:1]    # [bq, 1]
    bq, d = q.shape

    num_k = seq_k // block_k
    offset = seq_k - seq_q
    if causal:
        limit = jnp.clip(
            jax.lax.div((qi + 1) * block_q + offset + block_k - 1, block_k),
            0, num_k)
    else:
        limit = num_k

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, j, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, limit, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_q, seq_k):
    kj = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)  # [bk, d]
    vb = v_ref[0].astype(jnp.float32)
    bk, d = kb.shape

    num_q = seq_q // block_q
    offset = seq_k - seq_q
    if causal:
        # First query block whose causal band reaches this key block.
        start = jnp.clip(jax.lax.div(kj * block_k - offset, block_q),
                         0, num_q)
    else:
        start = 0

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0:1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), 0:1]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            s = _causal_mask(s, i, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, num_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [BH, Sq]
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, LANES))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq,
                          seq_k=sk),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq,
                          seq_k=sk),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper, [B, S, H, D] public layout
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def supported_shapes(query, key) -> bool:
    """True when the kernels handle these shapes (caller falls back else)."""
    sq, sk = query.shape[1], key.shape[1]
    d = query.shape[3]
    return sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256)


def flash_attention_pallas(query, key, value, causal: bool = False,
                           scale: Optional[float] = None,
                           block_q: int = DEFAULT_BLOCK_Q,
                           block_k: int = DEFAULT_BLOCK_K):
    """[B, S, H, D] flash attention via Pallas. Differentiable."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    if sq % min(block_q, sq) or sk % min(block_k, sk):
        raise ValueError(
            f"flash_attention_pallas needs seq lengths divisible by the "
            f"block sizes; got sq={sq}, sk={sk} (use supported_shapes())")
    hk = key.shape[2]
    if hk != h:  # grouped-query: broadcast kv heads
        rep = h // hk
        key = jnp.repeat(key, rep, axis=2)
        value = jnp.repeat(value, rep, axis=2)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    def to_bhsd(x, s):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    q = to_bhsd(query, sq)
    k = to_bhsd(key, sk)
    v = to_bhsd(value, sk)
    o = _flash_bhsd(q, k, v, float(scale), bool(causal), block_q, block_k)
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
