"""Flash attention as Pallas TPU kernels (fwd + bwd).

Online-softmax blocked attention (Dao et al.) tiled for the MXU. The key/
value sequence is STREAMED through VMEM via a third grid axis (TPU grids
iterate sequentially per core, so the online-softmax state lives in VMEM
scratch across the inner key-block steps) — VMEM usage is O(block) however
long the sequence, which is the point of flash attention. Backward
recomputes probabilities from the saved logsumexp (no O(S^2) residuals),
split into a dq kernel (inner loop over key blocks) and a dk/dv kernel
(inner loop over query blocks) so each output is accumulated by exactly one
program — no atomics.

Reference parity: ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:324``
(FlashAttnKernel → vendored CUTLASS flash-attn). Layout in/out is paddle's
[batch, seq, heads, head_dim]; internally [batch*heads, seq, head_dim].
Grouped-query attention keeps KV at [batch*kv_heads, seq, head_dim]: the
BlockSpec index maps route each query head to its shared KV tile, so GQA
never materializes repeated K/V (dK/dV fold the query-head groups after the
kernel). Causal masking is bottom-right aligned (query i attends keys <=
i + sk - sq), matching flash-attn decode semantics for sq != sk.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_pallas", "supported_shapes"]

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30

from ...core import flags as _flags  # noqa: E402

# Sweep hooks: set both to a 128-multiple (paddle.set_flags or
# FLAGS_flash_block_q/_k env vars) to override the tuned table; 0 = auto.
for _n in ("flash_block_q", "flash_block_k"):
    if _n not in _flags.get_flags():
        _flags.define_flag(_n, 0, "flash-attention block override (0=auto)")
if "flash_head_pack" not in _flags.get_flags():
    _flags.define_flag(
        "flash_head_pack", 1,
        "route d=64 dense-head attention to the head-packed kernel")


def _tuned_blocks(sq: int, sk: int, d: int):
    """Cached autotune result for this shape class, or None."""
    try:
        from .autotune import get_cache
        hit = get_cache().get("flash_attention", f"sq{sq}_sk{sk}_d{d}")
        return tuple(hit) if hit else None
    except Exception:
        return None


def tune_flash_blocks(query, key, value, causal: bool = False,
                      candidates=None, iters: int = 3):
    """On-device sweep of (block_q, block_k) for this shape; persists the
    winner so _pick_blocks uses it from then on (incl. at trace time).
    Call eagerly (not under jit) with representative inputs."""
    from .autotune import autotune
    b, sq, h, d = query.shape
    sk = key.shape[1]
    cands = candidates or [(256, 256), (512, 512), (512, 1024),
                           (1024, 512), (1024, 1024), (2048, 1024)]
    cands = [(bq, bk) for bq, bk in cands
             if sq % min(bq, sq) == 0 and sk % min(bk, sk) == 0]

    def run(cfg):
        bq, bk = cfg
        return flash_attention_pallas(query, key, value, causal=causal,
                                      block_q=bq, block_k=bk)

    return autotune("flash_attention", f"sq{sq}_sk{sk}_d{d}", cands, run,
                    iters=iters)


def _pick_blocks(sq: int, sk: int, d: int) -> tuple:
    """Autotuned (block_q, block_k) per head_dim for v5e-class VMEM: larger
    blocks amortize the sequential-grid overhead and keep the MXU busy
    (measured 1.8x over 128/128 at seq 1024, d 64). Returns the largest
    128-multiple <= the tuned target that divides the sequence length.
    ``flash_block_q``/``flash_block_k`` flags override (sweep hook)."""
    ov_q = int(_flags.flag("flash_block_q"))
    ov_k = int(_flags.flag("flash_block_k"))
    if ov_q or ov_k:
        if not (ov_q and ov_k):
            raise ValueError(
                f"flash_block_q/flash_block_k must be set together "
                f"(got q={ov_q}, k={ov_k}); set both or neither")
        if ov_q % 128 or ov_k % 128:
            raise ValueError(
                f"flash block overrides must be multiples of 128; got "
                f"q={ov_q}, k={ov_k}")
        tq, tk = ov_q, ov_k
    elif (tuned := _tuned_blocks(sq, sk, d)) is not None:
        # persistent autotune cache beats the static table (ref
        # phi/kernels/autotune/cache.h); populate via tune_flash_blocks()
        tq, tk = tuned
    elif d <= 64:
        tq, tk = 512, 1024
    elif d <= 128:
        # swept on the 254M GPT bench step (B16 S1024 H8): 1024/1024 =
        # 221.6ms vs 512/512 = 229.4ms (fewer grid steps, bigger MXU tiles)
        tq, tk = 1024, 1024
    else:
        tq, tk = 128, 256

    def fit(target, s):
        b = min(target, s)
        while b > 128 and s % b:
            b -= 128
        return b

    return fit(tq, sq), fit(tk, sk)


def _mix32(x):
    """murmur3 finalizer — a stateless uint32 mixer. Used for the dropout
    mask so forward and both backward kernels regenerate the IDENTICAL
    mask from (position, seed) with plain vector ops (the reference saves
    CUDA RNG state for the same purpose, flash_attn_kernel.cu:76; the
    pltpu hardware PRNG has no interpret-mode lowering, a jnp mixer runs
    everywhere and is exactly mirrorable in the dense reference)."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


def _keep_threshold(rate: float) -> int:
    """uint32 threshold: hash < threshold -> DROP (P = rate)."""
    return min(int(rate * 4294967296.0), 4294967295)


def _dropout_keepf(shape, bh, qi, kj, block_q, block_k, seq_q, seq_k,
                   seed, rate: float):
    """[shape] f32 factor: 0 where dropped, 1/keep_prob where kept."""
    q_pos = (jnp.uint32(qi) * jnp.uint32(block_q)
             + jax.lax.broadcasted_iota(jnp.uint32, shape, 0))
    k_pos = (jnp.uint32(kj) * jnp.uint32(block_k)
             + jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
    idx = (jnp.uint32(bh) * jnp.uint32(seq_q) + q_pos) \
        * jnp.uint32(seq_k) + k_pos
    h = _mix32(idx ^ seed.astype(jnp.uint32))
    keep = h >= jnp.uint32(_keep_threshold(rate))
    return keep.astype(jnp.float32) * (1.0 / (1.0 - rate))


def dropout_keep_dense(bh, sq, sk, seed, rate: float):
    """Dense mirror of the in-kernel mask: [BH, Sq, Sk] f32 keep factors.
    The CPU/reference path uses this so flash-with-dropout is bitwise
    consistent across backends under a fixed seed."""
    q_pos = jax.lax.broadcasted_iota(jnp.uint32, (bh, sq, sk), 1)
    k_pos = jax.lax.broadcasted_iota(jnp.uint32, (bh, sq, sk), 2)
    b_idx = jax.lax.broadcasted_iota(jnp.uint32, (bh, sq, sk), 0)
    idx = (b_idx * jnp.uint32(sq) + q_pos) * jnp.uint32(sk) + k_pos
    h = _mix32(idx ^ jnp.asarray(seed).astype(jnp.uint32))
    keep = h >= jnp.uint32(_keep_threshold(rate))
    return keep.astype(jnp.float32) * (1.0 / (1.0 - rate))


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    """Bottom-right-aligned causal mask (query i attends keys <= i + offset,
    offset = sk - sq)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=1)
    return jnp.where(q_pos + offset >= k_pos, s, NEG_INF)


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Forward: grid (bh, num_q_blocks, num_k_blocks), k innermost (streamed).
# ---------------------------------------------------------------------------

def _seg_mask(s, segq_ref, segk_ref):
    """Cross-segment entries get NEG_INF (packed-varlen attention).
    seg refs hold one int32 per position, [1, block] rows."""
    seg_q = segq_ref[0].T        # [bq, 1]
    seg_k = segk_ref[0]          # [1, bk]
    return jnp.where(seg_q == seg_k, s, NEG_INF)


def _ind01(cond):
    """bool -> {0,1} int32 for arithmetic-only index maps (works on both
    traced scalars and Python bools)."""
    return cond.astype(jnp.int32) if hasattr(cond, "astype") \
        else jnp.int32(cond)


def _can_pair(causal, sq, sk, nq, nk):
    """Shared fwd/bwd gate for the triangular enumeration — the two
    directions must pair under exactly the same condition."""
    return causal and sq == sk and nq == nk and nq % 2 == 0 and nq >= 2


def _paired_qi_kj(p, t, nq):
    """FlashAttention-2-style triangular enumeration for causal sq == sk:
    pair row p (p+1 in-band key blocks) with row nq-1-p (nq-p blocks) —
    every pair runs exactly nq+1 steps, and NO fully-masked block is ever
    fetched. Step t <= p works on (row p, key t); later steps on
    (row nq-1-p, key t-p-1). Arithmetic-only so it can serve as a
    BlockSpec index map."""
    c = _ind01(t <= p)
    qi = c * p + (1 - c) * (nq - 1 - p)
    kj = c * t + (1 - c) * (t - p - 1)
    return qi, kj


def _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, seed_ref,
                bias_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, segmented, block_q, block_k, seq_q, seq_k,
                dropout=0.0, biased=False, paired_nq=None):
    bh_id = pl.program_id(0)  # hoisted: program_id inside pl.when bodies
    # has no interpret-mode lowering
    if paired_nq is None:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)
        first = kj == 0
        last = kj == nk - 1
    else:
        p = pl.program_id(1)
        t = pl.program_id(2)
        qi, kj = _paired_qi_kj(p, t, paired_nq)
        first = jnp.logical_or(t == 0, t == p + 1)
        last = jnp.logical_or(t == p, t == paired_nq)
    offset = seq_k - seq_q

    @pl.when(first)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: key blocks fully above the diagonal contribute nothing (the
    # paired enumeration never visits them at all).
    in_band = jnp.asarray(True) if not causal or paired_nq is not None \
        else kj * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(in_band)
    def _step():
        # Dots run on the MXU in the input dtype (bf16-native) with fp32
        # accumulation via preferred_element_type — casting up to fp32 first
        # would quarter MXU throughput.
        q = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        s = _dot(q, kb, ((1,), (1,))) * scale  # [bq, bk] fp32
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        if segmented:
            s = _seg_mask(s, segq_ref, segk_ref)
        if biased:
            s = s + bias_ref[0]  # [1, bk] additive key bias, broadcast
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Zero out fully-masked entries: rows with no valid keys have
        # s == m_new == NEG_INF and exp(0) would silently average V.
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        # attention-prob dropout: the softmax DENOMINATOR uses the
        # undropped p; only the PV accumulation is masked+rescaled
        # (ref flash_attn_kernel.cu:44 — dropout on P, not on the output)
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = p
        if dropout > 0.0:
            pv = p * _dropout_keepf(p.shape, bh_id, qi, kj,
                                    block_q, block_k, seq_q, seq_k,
                                    seed_ref[0], dropout)
        acc_scr[...] = acc_scr[...] * alpha + _dot(pv.astype(vb.dtype), vb,
                                                   ((1,), (0,)))

    @pl.when(last)
    def _finish():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l[:, :1]).astype(o_ref.dtype)
        # lse is stored [BH, 1, Sq] (a single sublane row per program) —
        # broadcasting it across a 128-lane minor dim would cost 128x the
        # HBM for a per-row scalar.
        lse_ref[0] = (m_scr[...][:, :1] + jnp.log(l[:, :1])).T


def _segments_or_dummy(seg_q, seg_k, bh, sq, sk):
    """Kernels take segment refs unconditionally (one code path); the dense
    case feeds a [BH, 1, 1]-broadcastable dummy the specs tile for free."""
    segmented = seg_q is not None
    if not segmented:
        seg_q = jnp.zeros((bh, 1, sq), jnp.int32)
        seg_k = jnp.zeros((bh, 1, sk), jnp.int32)
    return segmented, seg_q, seg_k


def _kv_index(h: int, hk: int):
    """Grid row (= b*h + head) -> row of the [B*HK, S, D] KV array: query
    head g maps to KV head (g % h) // (h // hk) — grouped-query KV tiles
    are read through the index map, never materialized per query head."""
    rep = h // hk

    def index(b, i, j):
        return ((b // h) * hk + (b % h) // rep, j, 0)

    return index


def _bias_or_dummy(bias, b, sk):
    """bias: [B, 1, Sk] f32 additive key bias, or None -> dummy zeros."""
    biased = bias is not None
    if not biased:
        bias = jnp.zeros((b, 1, sk), jnp.float32)
    return biased, bias


def _fwd(q, k, v, scale, causal, block_q, block_k, num_heads,
         seg_q=None, seg_k=None, dropout=0.0, seed=None, bias=None):
    """q: [BH, S, D]; k,v: [B*HK, S, D] (+ optional [BH, 1, S] int32
    segment ids) -> (o [BH, Sq, D], lse [BH, 1, Sq] fp32)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    h = num_heads
    hk = k.shape[0] // (bh // h)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    segmented, seg_q, seg_k = _segments_or_dummy(seg_q, seg_k, bh, sq, sk)
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    biased, bias = _bias_or_dummy(bias, bh // h, sk)
    nq, nk = sq // block_q, sk // block_k
    # Triangular enumeration for causal equal-length attention: pair rows
    # so no fully-masked key block is ever DMA'd (grid nq*nk ->
    # (nq/2)*(nq+1), a ~2x program cut at large nq, 25% at nq=2).
    paired = _can_pair(causal, sq, sk, nq, nk)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             segmented=segmented, block_q=block_q,
                             block_k=block_k, seq_q=sq, seq_k=sk,
                             dropout=dropout, biased=biased,
                             paired_nq=nq if paired else None)
    kv_index = _kv_index(h, hk)
    if paired:
        grid = (bh, nq // 2, nq + 1)

        def qi_of(b, p, t):
            return _paired_qi_kj(p, t, nq)[0]

        def kj_of(b, p, t):
            return _paired_qi_kj(p, t, nq)[1]

        in_specs = [
            pl.BlockSpec((1, block_q, d),
                         lambda b, p, t: (b, qi_of(b, p, t), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, p, t: kv_index(b, qi_of(b, p, t),
                                                  kj_of(b, p, t))),
            pl.BlockSpec((1, block_k, d),
                         lambda b, p, t: kv_index(b, qi_of(b, p, t),
                                                  kj_of(b, p, t))),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, p, t: (b, 0, qi_of(b, p, t))),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, p, t: (b, 0, kj_of(b, p, t))),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, p, t, _h=num_heads:
                         (b // _h, 0, kj_of(b, p, t))),
        ]
        out_specs = [
            pl.BlockSpec((1, block_q, d),
                         lambda b, p, t: (b, qi_of(b, p, t), 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, p, t: (b, 0, qi_of(b, p, t))),
        ]
    else:
        grid = (bh, nq, nk)
        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j, _h=num_heads: (b // _h, 0, j)),
        ]
        out_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ]
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * sq * sk * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * sq * sk,
        ),
    )(q, k, v, seg_q, seg_k, seed, bias)
    return o, lse


# ---------------------------------------------------------------------------
# Backward dq: grid (bh, num_q_blocks, num_k_blocks), k streamed.
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   segq_ref, segk_ref, seed_ref, bias_ref, dq_ref, dq_scr,
                   *, scale, causal, segmented, block_q, block_k,
                   seq_q, seq_k, dropout=0.0, biased=False, paired_nq=None):
    bh_id = pl.program_id(0)
    if paired_nq is None:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)
        first = kj == 0
        last = kj == nk - 1
    else:
        p = pl.program_id(1)
        t = pl.program_id(2)
        qi, kj = _paired_qi_kj(p, t, paired_nq)
        first = jnp.logical_or(t == 0, t == p + 1)
        last = jnp.logical_or(t == p, t == paired_nq)
    offset = seq_k - seq_q

    @pl.when(first)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    in_band = jnp.asarray(True) if not causal or paired_nq is not None \
        else kj * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(in_band)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0].T    # [1, bq] row -> [bq, 1] column
        delta = delta_ref[0].T
        kb = k_ref[0]
        vb = v_ref[0]
        s = _dot(q, kb, ((1,), (1,))) * scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        if segmented:
            s = _seg_mask(s, segq_ref, segk_ref)
        if biased:
            s = s + bias_ref[0]
        p = jnp.exp(s - lse) * (s > NEG_INF / 2)
        dp = _dot(do, vb, ((1,), (1,)))
        if dropout > 0.0:
            # dP = dPdropped * keepf; delta = rowsum(dO*O) already equals
            # rowsum(P*dP) under dropout (O was built from the masked P)
            dp = dp * _dropout_keepf(p.shape, bh_id, qi, kj,
                                     block_q, block_k, seq_q, seq_k,
                                     seed_ref[0], dropout)
        ds = (p * (dp - delta) * scale).astype(kb.dtype)
        dq_scr[...] = dq_scr[...] + _dot(ds, kb, ((1,), (0,)))

    @pl.when(last)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# Backward dk/dv: grid (bh, num_k_blocks, num_q_blocks), q streamed.
# ---------------------------------------------------------------------------

def _paired_kj_qi(p, t, nq):
    """Column pairing for the dkv kernel (causal, sq == sk): column p
    (nq-p in-band query blocks) pairs with column nq-1-p (p+1 blocks) —
    nq+1 steps per pair, no masked block fetched."""
    ci = _ind01(t < nq - p)
    kj = ci * p + (1 - ci) * (nq - 1 - p)
    qi = ci * (p + t) + (1 - ci) * (t - 1)
    return kj, qi


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    segq_ref, segk_ref, seed_ref, bias_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr,
                    *, scale, causal, segmented, block_q, block_k,
                    seq_q, seq_k, num_q_blocks=None, paired_nq=None,
                    dropout=0.0, biased=False, gqa_dims=None):
    if paired_nq is not None:
        p = pl.program_id(1)
        t = pl.program_id(2)
        kj, qi = _paired_kj_qi(p, t, paired_nq)
        first = jnp.logical_or(t == 0, t == paired_nq - p)
        last = jnp.logical_or(t == paired_nq - p - 1, t == paired_nq)
    else:
        kj = pl.program_id(1)
        t = pl.program_id(2)
        nt = pl.num_programs(2)
        # Grouped-query: the last grid axis runs rep * num_q_blocks steps —
        # every query head sharing this KV head streams through, and dk/dv
        # accumulate across the whole group IN the scratch (no per-query-
        # head dk/dv materialization, no post-kernel fold).
        qi = t if num_q_blocks is None else t % num_q_blocks
        first = t == 0
        last = t == nt - 1
    offset = seq_k - seq_q

    bkv_id = pl.program_id(0)

    def query_bh():
        """Flat QUERY-head row for the dropout hash — must match the bh
        the fwd/dq kernels used for this (q, k) tile."""
        if gqa_dims is None:
            return bkv_id
        h, hk, rep = gqa_dims
        if rep == 1:
            return bkv_id
        return (bkv_id // hk) * h + (bkv_id % hk) * rep \
            + t // num_q_blocks

    @pl.when(first)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    in_band = jnp.asarray(True) if not causal or paired_nq is not None \
        else (qi + 1) * block_q - 1 + offset >= kj * block_k

    @pl.when(in_band)
    def _step():
        kb = k_ref[0]
        vb = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        lse = lse_ref[0].T    # [1, bq] row -> [bq, 1] column
        delta = delta_ref[0].T
        s = _dot(qb, kb, ((1,), (1,))) * scale  # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        if segmented:
            s = _seg_mask(s, segq_ref, segk_ref)
        if biased:
            s = s + bias_ref[0]
        p = jnp.exp(s - lse) * (s > NEG_INF / 2)
        pv = p
        dp = _dot(dob, vb, ((1,), (1,)))
        if dropout > 0.0:
            keepf = _dropout_keepf(p.shape, query_bh(), qi, kj, block_q,
                                   block_k, seq_q, seq_k, seed_ref[0],
                                   dropout)
            pv = p * keepf   # dV uses the MASKED probabilities
            dp = dp * keepf  # dP = dPdropped * keepf
        dv_scr[...] = dv_scr[...] + _dot(pv.astype(dob.dtype), dob,
                                         ((0,), (0,)))
        ds = (p * (dp - delta) * scale).astype(qb.dtype)
        dk_scr[...] = dk_scr[...] + _dot(ds, qb, ((0,), (0,)))

    @pl.when(last)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k, num_heads,
         seg_q=None, seg_k=None, dlse=None, dropout=0.0, seed=None,
         bias=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    h = num_heads
    b_ = bh // h
    hk = k.shape[0] // b_
    rep = h // hk
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    segmented, seg_q, seg_k = _segments_or_dummy(seg_q, seg_k, bh, sq, sk)
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    biased, bias = _bias_or_dummy(bias, b_, sk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [BH, Sq]
    delta = delta[:, None, :]  # [BH, 1, Sq] — matches the slim lse layout
    if dlse is not None:
        # lse cotangent (ring-attention merge differentiates through lse):
        # dL/ds_ij = p_ij (dp_ij - delta_i + dlse_i), so fold -dlse into the
        # delta the kernels already subtract.
        delta = delta - dlse.astype(jnp.float32)
    kv_index = _kv_index(h, hk)

    nqb, nkb = sq // block_q, sk // block_k
    dq_paired = _can_pair(causal, sq, sk, nqb, nkb)

    if dq_paired:
        def row_of(b, p, t):
            return _paired_qi_kj(p, t, nqb)[0]

        def col_of(b, p, t):
            return _paired_qi_kj(p, t, nqb)[1]

        dq_grid = (bh, nqb // 2, nqb + 1)
    else:
        def row_of(b, i, j):
            return i

        def col_of(b, i, j):
            return j

        dq_grid = (bh, nqb, nkb)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          segmented=segmented, block_q=block_q,
                          block_k=block_k, seq_q=sq, seq_k=sk,
                          dropout=dropout, biased=biased,
                          paired_nq=nqb if dq_paired else None),
        grid=dq_grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, i, j: (b, row_of(b, i, j), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: kv_index(b, row_of(b, i, j),
                                                  col_of(b, i, j))),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: kv_index(b, row_of(b, i, j),
                                                  col_of(b, i, j))),
            pl.BlockSpec((1, block_q, d),
                         lambda b, i, j: (b, row_of(b, i, j), 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, i, j: (b, 0, row_of(b, i, j))),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, i, j: (b, 0, row_of(b, i, j))),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, i, j: (b, 0, row_of(b, i, j))),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j: (b, 0, col_of(b, i, j))),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j, _h=h: (b // _h, 0,
                                                col_of(b, i, j))),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, i, j: (b, row_of(b, i, j), 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )(q, k, v, do, lse, delta, seg_q, seg_k, seed, bias)

    # dk/dv are emitted per KV head ([B*HK, Sk, D]): for GQA (rep > 1) the
    # last grid axis streams rep * num_q_blocks steps — every query head of
    # the group — and the group sum happens in the accumulation scratch, so
    # no rep-times dk/dv ever hits HBM (true zero-copy KV in backward too).
    # rep == 1 keeps identity index maps: the div/mod maps of the grouped
    # path cost ~20% step time on the dense bench (Mosaic prefetch).
    nq_blocks = sq // block_q
    bhk = b_ * hk

    dkv_paired = rep == 1 and dq_paired
    if dkv_paired:
        # Column pairing (causal, sq == sk, dense heads): grid
        # (bhk, nq/2, nq+1) never fetches a masked query block.
        def q_head(bkv, t):
            return bkv

        def q_index(b, j, t):
            return (b, _paired_kj_qi(j, t, nq_blocks)[1], 0)

        def stat_index(b, j, t):
            return (b, 0, _paired_kj_qi(j, t, nq_blocks)[1])

        def dkv_col(b, j, t):
            return (b, _paired_kj_qi(j, t, nq_blocks)[0], 0)

        def segk_index(b, j, t):
            return (q_head(b, t), 0, _paired_kj_qi(j, t, nq_blocks)[0])

        dkv_grid = (bhk, nq_blocks // 2, nq_blocks + 1)
    elif rep == 1:
        def q_head(bkv, t):
            return bkv

        def q_index(b, j, t):
            return (b, t, 0)

        def stat_index(b, j, t):
            return (b, 0, t)

        def dkv_col(b, j, t):
            return (b, j, 0)

        def segk_index(b, j, t):
            return (q_head(b, t), 0, j)

        dkv_grid = (bhk, sk // block_k, rep * nq_blocks)
    else:
        def q_head(bkv, t):
            # flat query-head row for grid coords (kv-head bkv, step t)
            return (bkv // hk) * h + (bkv % hk) * rep + t // nq_blocks

        def q_index(b, j, t):
            return (q_head(b, t), t % nq_blocks, 0)

        def stat_index(b, j, t):
            return (q_head(b, t), 0, t % nq_blocks)

        def dkv_col(b, j, t):
            return (b, j, 0)

        def segk_index(b, j, t):
            return (q_head(b, t), 0, j)

        dkv_grid = (bhk, sk // block_k, rep * nq_blocks)

    def q_spec(width):
        return pl.BlockSpec((1, width, d), q_index)

    def stat_spec():
        return pl.BlockSpec((1, 1, block_q), stat_index)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          segmented=segmented, block_q=block_q,
                          block_k=block_k, seq_q=sq, seq_k=sk,
                          num_q_blocks=nq_blocks,
                          paired_nq=nq_blocks if dkv_paired else None,
                          dropout=dropout, biased=biased,
                          gqa_dims=(h, hk, rep)),
        grid=dkv_grid,
        in_specs=[
            pl.BlockSpec((1, block_k, d), dkv_col),
            pl.BlockSpec((1, block_k, d), dkv_col),
            q_spec(block_q),
            q_spec(block_q),
            stat_spec(),
            stat_spec(),
            stat_spec(),
            pl.BlockSpec((1, 1, block_k), segk_index),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, j, t, _hk=hk: (b // _hk, 0,
                                                  dkv_col(b, j, t)[1])),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), dkv_col),
            pl.BlockSpec((1, block_k, d), dkv_col),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhk, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bhk, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )(k, v, q, do, lse, delta, seg_q, seg_k, seed, bias)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper, [B, S, H, D] public layout
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def _flash_bhsd(q, k, v, seg_q, seg_k, seed, bias, scale, causal, block_q,
                block_k, num_heads, dropout):
    o, _ = _fwd(q, k, v, scale, causal, block_q, block_k, num_heads,
                seg_q, seg_k, dropout=dropout, seed=seed, bias=bias)
    return o


def _flash_fwd_rule(q, k, v, seg_q, seg_k, seed, bias, scale, causal,
                    block_q, block_k, num_heads, dropout):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k, num_heads,
                  seg_q, seg_k, dropout=dropout, seed=seed, bias=bias)
    # Residuals carry checkpoint names so a remat policy can elect to SAVE
    # them: without this, jax.checkpoint re-runs the forward kernel inside
    # the backward (~0.96 ms/layer at the 1.3B shape) just to regenerate
    # (o, lse). See RecomputePolicy.DOTS_AND_FLASH.
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse, seg_q, seg_k, seed, bias)


def _flash_bwd_rule(scale, causal, block_q, block_k, num_heads, dropout,
                    res, do):
    q, k, v, o, lse, seg_q, seg_k, seed, bias = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k,
                      num_heads, seg_q, seg_k, dropout=dropout, seed=seed,
                      bias=bias)
    # the additive key bias is a mask, not a trained parameter: no cotangent
    return dq, dk, dv, None, None, None, None


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_bhsd_lse(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k,
                    num_heads):
    """Like _flash_bhsd but returns (o, lse [BH, 1, Sq] fp32) and is
    differentiable in BOTH outputs — the lse cotangent feeds ring-attention
    merges (distributed/context_parallel.py). No dropout (CP forbids it)."""
    return _fwd(q, k, v, scale, causal, block_q, block_k, num_heads,
                seg_q, seg_k)


def _flash_lse_fwd_rule(q, k, v, seg_q, seg_k, scale, causal, block_q,
                        block_k, num_heads):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k, num_heads,
                  seg_q, seg_k)
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (o, lse), (q, k, v, o, lse, seg_q, seg_k)


def _flash_lse_bwd_rule(scale, causal, block_q, block_k, num_heads, res, ct):
    do, dlse = ct
    q, k, v, o, lse, seg_q, seg_k = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k,
                      num_heads, seg_q, seg_k, dlse=dlse)
    return dq, dk, dv, None, None


_flash_bhsd_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def flash_attention_with_lse(query, key, value, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None):
    """[B, S, H, D] flash attention returning (o, lse [B, Sq, H] fp32).

    The blockwise-exact building block for ring context parallelism: two
    (o, lse) partials over disjoint key sets merge to the full softmax via
    lse' = logaddexp, o' = convex combination — and the custom VJP routes
    lse cotangents back through the kernels, so the merged result is
    differentiable end to end."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    auto_q, auto_k = _pick_blocks(sq, sk, d)
    block_q = block_q or auto_q
    block_k = block_k or auto_k
    if sq % min(block_q, sq) or sk % min(block_k, sk):
        raise ValueError(
            f"flash_attention_with_lse needs seq lengths divisible by the "
            f"block sizes; got sq={sq}, sk={sk}")
    hk = key.shape[2]
    if hk != h and (hk == 0 or h % hk):
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {hk}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    def to_bhsd(x, s, heads):
        return x.transpose(0, 2, 1, 3).reshape(b * heads, s, d)

    q = to_bhsd(query, sq, h)
    k = to_bhsd(key, sk, hk)
    v = to_bhsd(value, sk, hk)
    o, lse = _flash_bhsd_lse(q, k, v, None, None, float(scale), bool(causal),
                             block_q, block_k, h)
    o = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, sq).transpose(0, 2, 1)
    return o, lse


def supported_shapes(query, key) -> bool:
    """True when the kernels handle these shapes (caller falls back else)."""
    sq, sk = query.shape[1], key.shape[1]
    d = query.shape[3]
    return sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256)


def flash_attention_pallas(query, key, value, causal: bool = False,
                           scale: Optional[float] = None,
                           block_q: Optional[int] = None,
                           block_k: Optional[int] = None,
                           segment_ids=None, segment_ids_k=None,
                           dropout: float = 0.0, dropout_seed=None,
                           key_bias=None):
    """[B, S, H, D] flash attention via Pallas. Differentiable.

    Block sizes default to the autotuned table in ``_pick_blocks``; pass
    explicit ``block_q``/``block_k`` to override. Grouped-query attention
    (kv heads dividing query heads) reads shared KV tiles through the
    BlockSpec index map — no repeat materialization. ``segment_ids``
    ([B, Sq] int32) enables packed-varlen attention: tokens attend only
    keys with an equal segment id (the TPU-native form of
    flash_attn_unpadded — static shapes, sequences packed along S).
    ``segment_ids_k`` ([B, Sk]) defaults to ``segment_ids``
    (self-attention packing)."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    hk = key.shape[2]
    # Head-packed fast path for d=64 dense-head shapes (VERDICT r4 #3):
    # G heads per program on the lane axis — G-fold fewer programs, full-
    # lane DMAs. Skipped when the caller pins blocks (kernel sweeps/tests
    # target a specific grid of the unpacked kernel).
    if (block_q is None and block_k is None and d == 64 and hk == h
            and sq % 128 == 0 and sk % 128 == 0
            and int(_flags.flag("flash_head_pack"))):
        from .flash_attention_packed import (flash_attention_packed,
                                             pack_group)
        if pack_group(h):
            return flash_attention_packed(
                query, key, value, causal=causal, scale=scale,
                segment_ids=segment_ids, segment_ids_k=segment_ids_k,
                dropout=dropout, dropout_seed=dropout_seed,
                key_bias=key_bias)
    auto_q, auto_k = _pick_blocks(sq, sk, d)
    block_q = block_q or auto_q
    block_k = block_k or auto_k
    if sq % min(block_q, sq) or sk % min(block_k, sk):
        raise ValueError(
            f"flash_attention_pallas needs seq lengths divisible by the "
            f"block sizes; got sq={sq}, sk={sk} (use supported_shapes())")
    if hk != h and (hk == 0 or h % hk):
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {hk} "
            f"(grouped-query)")
    if _flags.flag("static_analysis") != "off":
        # TPU-constraint pre-check of the chosen block config (P0xx rules)
        from ...analysis import pallas_check as _pc
        for _bwd in (False, True):
            _pc.enforce(_pc.spec_for_flash(sq, sk, d, block_q, block_k,
                                           query.dtype, bwd=_bwd),
                        where="flash_attention_pallas")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    def to_bhsd(x, s, heads):
        return x.transpose(0, 2, 1, 3).reshape(b * heads, s, d)

    # Grouped-query KV stays [B*HK, S, D]: the kernels' BlockSpec index map
    # routes each query head to its shared KV tile (no repeat materialized).
    q = to_bhsd(query, sq, h)
    k = to_bhsd(key, sk, hk)
    v = to_bhsd(value, sk, hk)
    seg_q = seg_k = None
    if segment_ids is not None:
        def per_head(seg, s, what):
            from ...analysis._jaxpr_utils import fmt_shape
            seg = jnp.asarray(seg, jnp.int32)
            if seg.shape != (b, s):
                raise ValueError(
                    f"{what} must be [batch, seq] = {fmt_shape((b, s))}; "
                    f"got {fmt_shape(seg.shape)}")
            return jnp.repeat(seg[:, None, :], h,
                              axis=1).reshape(b * h, 1, s)
        seg_q = per_head(segment_ids, sq, "segment_ids")
        seg_k = seg_q if segment_ids_k is None and sq == sk else \
            per_head(segment_ids_k if segment_ids_k is not None
                     else segment_ids, sk, "segment_ids_k")
    if dropout > 0.0:
        if dropout_seed is None:
            from ...core.random import next_key
            dropout_seed = jax.random.randint(
                next_key(), (1,), 0, 2 ** 31 - 1, dtype=jnp.int32)
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    bias = None
    if key_bias is not None:
        bias = jnp.asarray(key_bias, jnp.float32).reshape(b, 1, sk)
    o = _flash_bhsd(q, k, v, seg_q, seg_k, seed, bias, float(scale),
                    bool(causal), block_q, block_k, h, float(dropout))
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
