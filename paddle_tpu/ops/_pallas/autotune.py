"""Kernel autotune harness with a persistent on-disk cache.

Reference parity: ``paddle/phi/kernels/autotune/cache.h:1`` (AlgorithmsCache
— runtime-measured algo choices keyed by shape/dtype, serialized across
runs) and ``switch_autotune.h`` (global enable switch). TPU-native form:
the tunable is a Pallas kernel's block configuration; measurement runs the
real kernel on-device eagerly (compile + time), and the winner is stored in
a JSON cache keyed by (kernel, chip, shape-key) that ``_pick_blocks``-style
selectors consult BEFORE their static tables. Autotuning happens at eager
level — under jit the cached (static) choice is read at trace time, which
is exactly when block sizes must be known.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ...core import flags as _flags

__all__ = ["AutotuneCache", "get_cache", "autotune", "chip_kind"]

# Bumped when the measurement methodology changes; entries from older
# schemes are ignored (a wall-clock-era cache entry silently regressed the
# GPT bench by 22% in round 3 — never trust stale measurements).
CACHE_SCHEMA = 2

for _n, _d, _h in [
    ("kernel_autotune", 1, "consult the persistent kernel-autotune cache"),
    ("kernel_autotune_cache_path", "",
     "override the autotune cache file location"),
]:
    try:
        _flags.flag(_n)
    except KeyError:
        _flags.define_flag(_n, _d, _h)


def chip_kind() -> str:
    try:
        return jax.devices()[0].device_kind.replace(" ", "_")
    except Exception:
        return "unknown"


def _default_path() -> str:
    p = str(_flags.flag("kernel_autotune_cache_path") or "")
    if p:
        return p
    p = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "autotune.json")


class AutotuneCache:
    """(kernel, chip, key) -> config, persisted as JSON (ref cache.h
    AlgorithmsCache + autotune_cache_utils serialization)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or _default_path()
        self._data: Dict[str, Any] = {}
        self._loaded = False

    def _key(self, kernel: str, key) -> str:
        return f"{kernel}|{chip_kind()}|{key}"

    def load(self):
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self.path) as f:
                self._data = json.load(f)
        except (OSError, ValueError):
            self._data = {}

    def save(self):
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass  # cache is an optimization; never fail the program

    def get(self, kernel: str, key) -> Optional[Any]:
        if not _flags.flag("kernel_autotune"):
            return None
        self.load()
        ent = self._data.get(self._key(kernel, key))
        if not ent or ent.get("schema") != CACHE_SCHEMA:
            return None
        return ent["config"]

    def put(self, kernel: str, key, config, measured_ms: float):
        self.load()
        self._data[self._key(kernel, key)] = {
            "config": config,
            "measured_ms": round(measured_ms, 4),
            "tuned_at": time.strftime("%Y-%m-%d %H:%M:%S"),
            "schema": CACHE_SCHEMA,
        }
        self.save()

    def stats(self):
        self.load()
        return dict(self._data)


_cache: Optional[AutotuneCache] = None


def get_cache() -> AutotuneCache:
    global _cache
    if _cache is None:
        _cache = AutotuneCache()
    return _cache


def _device_ms_from_trace(log_dir: str) -> Optional[float]:
    """Total device self-time (ms) of the newest captured trace."""
    try:
        from ...profiler.statistic import device_statistics
        stats = device_statistics(log_dir, top=1)
        if stats is None:
            return None
        by_cat, _ = stats
        return sum(by_cat.values())
    except Exception:
        return None


def _measure(run: Callable[[], Any], warmup: int, iters: int) -> float:
    """Measure DEVICE time of a kernel launch via a profiler trace —
    host-side wall clock is useless through the axon tunnel (per-dispatch
    latency dwarfs single-kernel device time; PERF.md measurement note).
    Falls back to walled enqueue-then-sync when no trace parser exists."""
    import shutil
    import tempfile

    def sync(r):
        leaves = jax.tree_util.tree_leaves(r)
        return float(jnp.sum(leaves[0].astype(jnp.float32)))

    for _ in range(max(warmup, 1)):
        sync(run())
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_autotune_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(iters):
                r = run()
            sync(r)
        dev_ms = _device_ms_from_trace(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if dev_ms is not None:
        return dev_ms / iters
    # host wall-clock is only the FALLBACK when no profiler trace landed;
    # it runs eagerly (synced), never under jit
    t0 = time.perf_counter()  # repo-lint: allow R001
    for _ in range(iters):
        r = run()
    sync(r)
    return (time.perf_counter() - t0) / iters * 1e3  # repo-lint: allow R001


def autotune(kernel: str, key, candidates: Sequence[Any],
             run_fn: Callable[[Any], Any], warmup: int = 1, iters: int = 3,
             measure: Optional[Callable[[Callable[[], Any]], float]] = None,
             cache: Optional[AutotuneCache] = None):
    """Sweep candidates on-device, persist and return the winner.

    run_fn(config) -> result (device arrays). A cached entry short-circuits
    the sweep. Candidates that raise are skipped (unsupported shapes)."""
    c = cache or get_cache()
    hit = c.get(kernel, key)
    if hit is not None:
        return hit
    meas = measure or (lambda run: _measure(run, warmup, iters))
    best_cfg, best_ms = None, float("inf")
    for cfg in candidates:
        try:
            ms = meas(lambda: run_fn(cfg))
        except Exception:
            continue
        if ms < best_ms:
            best_cfg, best_ms = cfg, ms
    if best_cfg is None:
        raise ValueError(f"autotune({kernel}): no candidate ran for {key}")
    c.put(kernel, key, best_cfg, best_ms)
    return best_cfg
