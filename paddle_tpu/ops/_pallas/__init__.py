"""Pallas TPU kernels — the hand-written hot ops.

The reference vendors CUTLASS flash-attention and hand-fused CUDA kernels
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu``, ``fluid/operators/fused/``).
Here the equivalents are Pallas kernels tiled for the MXU; everything else is
left to XLA fusion.

Modules: ``flash_attention`` / ``flash_attention_packed`` (attention),
``fused_matmul_bn`` (isolated 1x1+BN prototype), ``conv`` (the conv kernel
family with in-kernel BN epilogues — fwd/dgrad/wgrad, FLAGS_pallas_conv),
``autotune`` (persistent device-time block-config cache).
"""
