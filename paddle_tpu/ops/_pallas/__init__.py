"""Pallas TPU kernels — the hand-written hot ops.

The reference vendors CUTLASS flash-attention and hand-fused CUDA kernels
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu``, ``fluid/operators/fused/``).
Here the equivalents are Pallas kernels tiled for the MXU; everything else is
left to XLA fusion.
"""
