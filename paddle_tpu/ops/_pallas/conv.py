"""Pallas TPU conv kernel family with in-kernel BN epilogues (fwd +
dgrad/wgrad) — the cuDNN-class fused conv library the reference keeps at
``paddle/phi/kernels/gpudnn/conv_kernel.cu`` + ``conv_cudnn_v7.h``.

Why this exists (VERDICT r5 missing #2): ResNet-50 is the repo's only
failing perf gate (0.773x vs the 0.9x north star) and PERF.md r5 proved
the remaining ~12 GB/step cannot come from graph restructuring — XLA
already fuses BN stats as conv-epilogue tuple outputs, so the bytes can
only move if a *kernel* changes the traffic. These kernels do, for the
byte-dominant ResNet shape classes:

- **1x1 conv as matmul** (``[N*H*W, Cin] @ [Cin, Cout]``) with the BN
  apply + ReLU of the *previous* layer fused as an in-kernel prologue and
  the per-channel (sum, sumsq) of the output accumulated in VMEM scratch
  as an epilogue: the normalized activation never round-trips HBM, and
  the next BN's stats are free.
- **NHWC 3x3 (stride 1 and 2)** via im2col-in-kernel block loads: the
  padded image rides VMEM once per batch index, each grid step assembles
  its nine shifted tap tiles in VMEM (never in HBM — the classic im2col
  blowup stays on-chip) and feeds the MXU; same prologue/epilogue hooks.
- The **dgrad/wgrad backward pair**: dgrad reuses the forward kernels on
  rotated taps (stride-2 via an outside dy dilation), wgrad accumulates
  ``a^T @ dy`` per tap in an f32 VMEM scratch across the grid, with the
  BN+ReLU prologue *recomputed in-kernel* from the raw input
  (flash-attention-style remat — only the pre-BN tensor is ever saved).

Routing: ``FLAGS_pallas_conv`` (default OFF until a measured win — see
the ``BENCH_PALLAS_CONV=1`` A/B hook in ``bench.py``) swaps these kernels
into the deferred-BN units of ``nn/fused_conv_bn.py``; unsupported shapes
(groups, dilation, other kernel sizes, over-VMEM configs) fall back to
the lax path inside the same custom_vjp boundaries. On non-TPU backends
the kernels run in Pallas interpret mode, so the whole family is
CPU-verifiable (tier-1 parity tests in ``tests/test_pallas_conv.py``).

Block configs consult the persistent device-time autotune cache
(``ops/_pallas/autotune.py``; keys ``pallas_conv1x1`` / ``pallas_conv3x3``)
before the static divisor tables; ``tune_conv_shapes`` sweeps and
persists winners on a real chip. Declared configurations are checked
against the TPU constraints (16MB scoped VMEM incl. im2col tiles,
(8,128) tiles, grid divisibility) by ``analysis/pallas_check.py``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core import flags as _flags

__all__ = [
    "conv2d", "conv2d_fwd", "conv2d_dgrad", "conv2d_wgrad", "supports",
    "pallas_conv_enabled", "tune_conv_shapes", "RESNET50_TOP3_SHAPES",
]

if "pallas_conv" not in _flags.get_flags():
    _flags.define_flag(
        "pallas_conv", 0,
        "route supported convs (1x1-as-matmul, NHWC 3x3 s1/s2) through "
        "the Pallas conv kernel family with in-kernel BN epilogues "
        "(default off until a measured win; A/B via BENCH_PALLAS_CONV=1)")

# The three byte-dominant conv shape classes of the r5 ResNet-50 profile
# (tools/resnet_bytes.py, batch 256, bw-derived GB/step: the stage-1
# 56x56 activations dominate — the 1x1 reduce/expand pair around the
# bottleneck and the 3x3 workhorse). (kind, n, h, w, cin, cout, stride).
RESNET50_TOP3_SHAPES = (
    ("conv1x1", 256, 56, 56, 256, 64, 1),
    ("conv1x1", 256, 56, 56, 64, 256, 1),
    ("conv3x3", 256, 56, 56, 64, 64, 1),
)

_MM_BLOCKS = (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
_C3_BLOCKS = (16, 8, 4, 2, 1)


def _interpret_default() -> bool:
    """Real Mosaic on TPU-class backends, interpreter everywhere else —
    the CPU-verifiability contract of the family."""
    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:
        return True


def pallas_conv_enabled() -> bool:
    return bool(_flags.flag("pallas_conv"))


def _tuned(kernel: str, key: str) -> Optional[int]:
    try:
        from .autotune import get_cache
        hit = get_cache().get(kernel, key)
        return int(hit) if hit else None
    except Exception:
        return None


def _largest_divisor(n: int, candidates: Sequence[int]) -> int:
    for b in candidates:
        if n % b == 0:
            return b
    return 1


def _mm_key(m, cin, cout, dtype) -> str:
    return f"m{m}_ci{cin}_co{cout}_{jnp.dtype(dtype).name}"


def _c3_key(n, h, w, c, k, stride, dtype) -> str:
    return f"n{n}_h{h}_w{w}_c{c}_k{k}_s{stride}_{jnp.dtype(dtype).name}"


def _pick_block_m(m: int, cin: int, cout: int, dtype) -> int:
    hit = _tuned("pallas_conv1x1", _mm_key(m, cin, cout, dtype))
    if hit and m % hit == 0:
        return hit
    return _largest_divisor(m, _MM_BLOCKS)


def _pick_block_h(ho: int, n, h, w, c, k, stride, dtype) -> int:
    hit = _tuned("pallas_conv3x3", _c3_key(n, h, w, c, k, stride, dtype))
    if hit and ho % hit == 0:
        return hit
    return _largest_divisor(ho, _C3_BLOCKS)


def _enforce(spec, where: str):
    from ...analysis.pallas_check import enforce
    enforce(spec, where=where)


# ---------------------------------------------------------------------------
# 1x1-as-matmul kernels (fwd doubles as dgrad on transposed weights)
# ---------------------------------------------------------------------------

def _mm_kernel(x_ref, w_ref, scale_ref, shift_ref, y_ref, s_ref, ss_ref,
               s_scr, ss_scr, *, prologue: bool, act: str, stats: bool,
               nm: int):
    i = pl.program_id(1)  # row-block index (inner grid axis)
    xb = x_ref[0]
    if prologue:
        xb = xb * scale_ref[0].astype(xb.dtype) + \
            shift_ref[0].astype(xb.dtype)
        if act == "relu":
            xb = jnp.maximum(xb, 0)
    acc = lax.dot_general(xb, w_ref[0], (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    y_ref[0] = acc.astype(y_ref.dtype)
    if stats:
        @pl.when(i == 0)
        def _init():
            s_scr[...] = jnp.zeros_like(s_scr)
            ss_scr[...] = jnp.zeros_like(ss_scr)

        s_scr[...] += jnp.sum(acc, axis=0, keepdims=True)
        ss_scr[...] += jnp.sum(acc * acc, axis=0, keepdims=True)

        @pl.when(i == nm - 1)
        def _fin():
            s_ref[0] = s_scr[...]
            ss_ref[0] = ss_scr[...]
    else:
        @pl.when(i == nm - 1)
        def _fin0():
            s_ref[0] = jnp.zeros(s_ref.shape[1:], s_ref.dtype)
            ss_ref[0] = jnp.zeros(ss_ref.shape[1:], ss_ref.dtype)


def _mm(x2, w2, scale, shift, prologue: bool, act: str, stats: bool,
        block_m: int, interpret: bool):
    m, cin = x2.shape
    cout = w2.shape[1]
    block_m = min(block_m, m)
    nm = m // block_m
    if scale is None:
        scale = jnp.zeros((cin,), jnp.float32)
        shift = jnp.zeros((cin,), jnp.float32)
    kern = functools.partial(_mm_kernel, prologue=prologue, act=act,
                             stats=stats, nm=nm)
    y, s, ss = pl.pallas_call(
        kern,
        grid=(1, nm),  # trivial outer axis keeps the row loop innermost
        in_specs=[
            pl.BlockSpec((1, block_m, cin), lambda j, i: (0, i, 0)),
            pl.BlockSpec((1, cin, cout), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cin), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cin), lambda j, i: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_m, cout), lambda j, i: (0, i, 0)),
            pl.BlockSpec((1, 1, cout), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cout), lambda j, i: (0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, m, cout), x2.dtype),
            jax.ShapeDtypeStruct((1, 1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, 1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * m * cin * cout,
            bytes_accessed=(x2.size * x2.dtype.itemsize +
                            m * cout * x2.dtype.itemsize +
                            w2.size * w2.dtype.itemsize),
            transcendentals=0),
        interpret=interpret,
    )(x2[None], w2[None], scale[None, None].astype(jnp.float32),
      shift[None, None].astype(jnp.float32))
    return y[0], s[0, 0], ss[0, 0]


def _mm_wgrad_kernel(x_ref, dy_ref, scale_ref, shift_ref, dw_ref, acc_scr,
                     *, prologue: bool, act: str, nm: int):
    i = pl.program_id(1)
    xb = x_ref[0]
    if prologue:
        xb = xb * scale_ref[0].astype(xb.dtype) + \
            shift_ref[0].astype(xb.dtype)
        if act == "relu":
            xb = jnp.maximum(xb, 0)

    @pl.when(i == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += lax.dot_general(xb, dy_ref[0], (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    @pl.when(i == nm - 1)
    def _fin():
        dw_ref[0] = acc_scr[...]


def _mm_wgrad(x2, dy2, scale, shift, prologue: bool, act: str,
              block_m: int, interpret: bool):
    m, cin = x2.shape
    cout = dy2.shape[1]
    block_m = min(block_m, m)
    nm = m // block_m
    if scale is None:
        scale = jnp.zeros((cin,), jnp.float32)
        shift = jnp.zeros((cin,), jnp.float32)
    kern = functools.partial(_mm_wgrad_kernel, prologue=prologue, act=act,
                             nm=nm)
    dw = pl.pallas_call(
        kern,
        grid=(1, nm),
        in_specs=[
            pl.BlockSpec((1, block_m, cin), lambda j, i: (0, i, 0)),
            pl.BlockSpec((1, block_m, cout), lambda j, i: (0, i, 0)),
            pl.BlockSpec((1, 1, cin), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, cin), lambda j, i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, cin, cout), lambda j, i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, cin, cout), jnp.float32),
        scratch_shapes=[pltpu.VMEM((cin, cout), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * m * cin * cout,
            bytes_accessed=(x2.size * x2.dtype.itemsize +
                            dy2.size * dy2.dtype.itemsize +
                            cin * cout * 4),
            transcendentals=0),
        interpret=interpret,
    )(x2[None], dy2[None], scale[None, None].astype(jnp.float32),
      shift[None, None].astype(jnp.float32))
    return dw[0]


# ---------------------------------------------------------------------------
# NHWC 3x3 kernels: im2col assembled in VMEM, nine MXU taps per block
# ---------------------------------------------------------------------------

def _c3_prologue(xa, scale_ref, shift_ref, prologue: bool, act: str,
                 pad: int, h_valid: int, w_valid: int):
    """In-kernel BN apply (+ReLU) masked to the pre-padding valid region:
    the zero-padded border must stay zero THROUGH the affine prologue
    (relu(0*scale+shift) != 0 in general)."""
    if not prologue:
        return xa
    a = xa * scale_ref[0].astype(xa.dtype) + shift_ref[0].astype(xa.dtype)
    if act == "relu":
        a = jnp.maximum(a, 0)
    hp, wp = xa.shape[0], xa.shape[1]
    rows = lax.broadcasted_iota(jnp.int32, (hp, wp), 0)
    cols = lax.broadcasted_iota(jnp.int32, (hp, wp), 1)
    valid = ((rows >= pad) & (rows < pad + h_valid)
             & (cols >= pad) & (cols < pad + w_valid))
    return jnp.where(valid[:, :, None], a, jnp.zeros_like(a))


def _c3_taps(a, base, stride: int, block_h: int, wo: int, c: int):
    """Yield the nine [block_h*wo, c] im2col tap tiles for output-row
    block starting at input row ``base`` (VMEM-resident; never in HBM)."""
    rows_in = (block_h - 1) * stride + 1
    cols_in = (wo - 1) * stride + 1
    for t in range(9):
        dh, dw = divmod(t, 3)
        sub = lax.dynamic_slice(a, (base + dh, dw, 0), (rows_in, cols_in, c))
        yield t, sub[::stride, ::stride, :].reshape(block_h * wo, c)


def _c3_kernel(x_ref, w_ref, scale_ref, shift_ref, y_ref, s_ref, ss_ref,
               s_scr, ss_scr, *, prologue: bool, act: str, stats: bool,
               stride: int, block_h: int, wo: int, pad: int, h_valid: int,
               w_valid: int, n_total: int, nh: int):
    n = pl.program_id(0)
    i = pl.program_id(1)
    c = x_ref.shape[3]
    k = y_ref.shape[3]
    a = _c3_prologue(x_ref[0], scale_ref, shift_ref, prologue, act, pad,
                     h_valid, w_valid)
    acc = jnp.zeros((block_h * wo, k), jnp.float32)
    for t, tap in _c3_taps(a, i * block_h * stride, stride, block_h, wo, c):
        acc = acc + lax.dot_general(tap, w_ref[t], (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    y_ref[0] = acc.reshape(block_h, wo, k).astype(y_ref.dtype)
    if stats:
        @pl.when((n == 0) & (i == 0))
        def _init():
            s_scr[...] = jnp.zeros_like(s_scr)
            ss_scr[...] = jnp.zeros_like(ss_scr)

        s_scr[...] += jnp.sum(acc, axis=0, keepdims=True)
        ss_scr[...] += jnp.sum(acc * acc, axis=0, keepdims=True)

        @pl.when((n == n_total - 1) & (i == nh - 1))
        def _fin():
            s_ref[...] = s_scr[...]
            ss_ref[...] = ss_scr[...]
    else:
        @pl.when((n == n_total - 1) & (i == nh - 1))
        def _fin0():
            s_ref[...] = jnp.zeros(s_ref.shape, s_ref.dtype)
            ss_ref[...] = jnp.zeros(ss_ref.shape, ss_ref.dtype)


def _c3(xp, wt, scale, shift, prologue: bool, act: str, stats: bool,
        stride: int, block_h: int, h_valid: int, w_valid: int,
        interpret: bool):
    """xp: [N, Hp, Wp, C] pre-padded input; wt: [9, C, K] tap matrices.
    Returns (y [N, Ho, Wo, K], s [K] f32, ss [K] f32)."""
    n, hp, wp, c = xp.shape
    k = wt.shape[2]
    ho = (hp - 3) // stride + 1
    wo = (wp - 3) // stride + 1
    block_h = min(block_h, ho)
    nh = ho // block_h
    if scale is None:
        scale = jnp.zeros((c,), jnp.float32)
        shift = jnp.zeros((c,), jnp.float32)
    kern = functools.partial(
        _c3_kernel, prologue=prologue, act=act, stats=stats, stride=stride,
        block_h=block_h, wo=wo, pad=1, h_valid=h_valid, w_valid=w_valid,
        n_total=n, nh=nh)
    y, s, ss = pl.pallas_call(
        kern,
        grid=(n, nh),
        in_specs=[
            # whole padded image per batch index: Pallas re-DMAs only when
            # the block index changes, so the image loads once per n
            pl.BlockSpec((1, hp, wp, c), lambda b, i: (b, 0, 0, 0)),
            pl.BlockSpec((9, c, k), lambda b, i: (0, 0, 0)),
            pl.BlockSpec((1, c), lambda b, i: (0, 0)),
            pl.BlockSpec((1, c), lambda b, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_h, wo, k), lambda b, i: (b, i, 0, 0)),
            pl.BlockSpec((1, k), lambda b, i: (0, 0)),
            pl.BlockSpec((1, k), lambda b, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, k), xp.dtype),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, k), jnp.float32),
            pltpu.VMEM((1, k), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * 9 * n * ho * wo * c * k,
            bytes_accessed=(xp.size * xp.dtype.itemsize +
                            n * ho * wo * k * xp.dtype.itemsize +
                            wt.size * wt.dtype.itemsize),
            transcendentals=0),
        interpret=interpret,
    )(xp, wt, scale[None].astype(jnp.float32),
      shift[None].astype(jnp.float32))
    return y, s[0], ss[0]


def _c3_wgrad_kernel(x_ref, dy_ref, scale_ref, shift_ref, dw_ref, acc_scr,
                     *, prologue: bool, act: str, stride: int, block_h: int,
                     wo: int, pad: int, h_valid: int, w_valid: int,
                     n_total: int, nh: int):
    n = pl.program_id(0)
    i = pl.program_id(1)
    c = x_ref.shape[3]
    k = dy_ref.shape[3]
    a = _c3_prologue(x_ref[0], scale_ref, shift_ref, prologue, act, pad,
                     h_valid, w_valid)
    dyb = dy_ref[0].reshape(block_h * wo, k)

    @pl.when((n == 0) & (i == 0))
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    for t, tap in _c3_taps(a, i * block_h * stride, stride, block_h, wo, c):
        acc_scr[t] += lax.dot_general(tap, dyb, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)

    @pl.when((n == n_total - 1) & (i == nh - 1))
    def _fin():
        dw_ref[...] = acc_scr[...]


def _c3_wgrad(xp, dy, scale, shift, prologue: bool, act: str, stride: int,
              block_h: int, h_valid: int, w_valid: int, interpret: bool):
    """Returns dw tap matrices [9, C, K] f32 accumulated across the grid."""
    n, hp, wp, c = xp.shape
    k = dy.shape[3]
    ho, wo = dy.shape[1], dy.shape[2]
    block_h = min(block_h, ho)
    nh = ho // block_h
    if scale is None:
        scale = jnp.zeros((c,), jnp.float32)
        shift = jnp.zeros((c,), jnp.float32)
    kern = functools.partial(
        _c3_wgrad_kernel, prologue=prologue, act=act, stride=stride,
        block_h=block_h, wo=wo, pad=1, h_valid=h_valid, w_valid=w_valid,
        n_total=n, nh=nh)
    dw = pl.pallas_call(
        kern,
        grid=(n, nh),
        in_specs=[
            pl.BlockSpec((1, hp, wp, c), lambda b, i: (b, 0, 0, 0)),
            pl.BlockSpec((1, block_h, wo, k), lambda b, i: (b, i, 0, 0)),
            pl.BlockSpec((1, c), lambda b, i: (0, 0)),
            pl.BlockSpec((1, c), lambda b, i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((9, c, k), lambda b, i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((9, c, k), jnp.float32),
        scratch_shapes=[pltpu.VMEM((9, c, k), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * 9 * n * ho * wo * c * k,
            bytes_accessed=(xp.size * xp.dtype.itemsize +
                            dy.size * dy.dtype.itemsize + 9 * c * k * 4),
            transcendentals=0),
        interpret=interpret,
    )(xp, dy, scale[None].astype(jnp.float32),
      shift[None].astype(jnp.float32))
    return dw


# ---------------------------------------------------------------------------
# Host-side entries (raw, non-differentiable; the fused_conv_bn units and
# the conv2d custom_vjp below drive autodiff through dgrad/wgrad)
# ---------------------------------------------------------------------------

def _fwd_taps(w, dtype):
    """OIHW [K, C, 3, 3] -> tap matrices [9, C, K]."""
    return jnp.transpose(w, (2, 3, 1, 0)).reshape(9, w.shape[1],
                                                  w.shape[0]).astype(dtype)


def conv2d_fwd(x, w, scale=None, shift=None, act: str = "none",
               stride: Tuple[int, int] = (1, 1),
               padding: Tuple[int, int] = (0, 0), stats: bool = True,
               block_m: Optional[int] = None, block_h: Optional[int] = None,
               interpret: Optional[bool] = None):
    """Fused conv forward: ``conv(act(x*scale+shift), w)`` plus the
    per-channel (sum, sumsq) of the output, one HBM pass.

    x: [N, H, W, C] NHWC; w: OIHW [K, C, kh, kw] with kh == kw in {1, 3}
    (1x1 requires padding (0, 0), 3x3 requires padding (1, 1)).
    scale/shift: optional [C] f32 prologue (None = no prologue);
    act: 'none' | 'relu' (prologue activation, ignored without prologue).
    Returns (y [N, Ho, Wo, K], s [K] f32, ss [K] f32); s/ss are zeros
    when ``stats=False``.
    """
    interpret = _interpret_default() if interpret is None else interpret
    prologue = scale is not None
    k = w.shape[2]
    if k == 1:
        xs = x if stride == (1, 1) else x[:, ::stride[0], ::stride[1], :]
        n, h, ww, c = xs.shape
        m = n * h * ww
        bm = block_m or _pick_block_m(m, c, w.shape[0], x.dtype)
        _enforce_mm_spec(m, c, w.shape[0], bm, x.dtype, wgrad=False)
        w2 = w.reshape(w.shape[0], c).T.astype(x.dtype)
        y2, s, ss = _mm(xs.reshape(m, c), w2, scale, shift, prologue, act,
                        stats, bm, interpret)
        return y2.reshape(n, h, ww, w.shape[0]), s, ss
    n, h, ww, c = x.shape
    s_ = stride[0]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    ho = (h + 2 - 3) // s_ + 1
    bh = block_h or _pick_block_h(ho, n, h, ww, c, w.shape[0], s_, x.dtype)
    _enforce_c3_spec(n, h, ww, c, w.shape[0], bh, s_, x.dtype, wgrad=False)
    return _c3(xp, _fwd_taps(w, x.dtype), scale, shift, prologue, act,
               stats, s_, bh, h, ww, interpret)


def conv2d_dgrad(dy, w, x_shape, stride: Tuple[int, int] = (1, 1),
                 padding: Tuple[int, int] = (0, 0),
                 block_m: Optional[int] = None,
                 block_h: Optional[int] = None,
                 interpret: Optional[bool] = None):
    """Input gradient: the transposed conv run through the SAME kernels
    (1x1: matmul with w^T; 3x3: forward kernel on 180-degree-rotated taps,
    stride 2 via an outside dilation of dy)."""
    interpret = _interpret_default() if interpret is None else interpret
    k = w.shape[2]
    s_ = stride[0]
    if k == 1:
        n, ho, wo, kk = dy.shape
        m = n * ho * wo
        c = w.shape[1]
        bm = block_m or _pick_block_m(m, kk, c, dy.dtype)
        _enforce_mm_spec(m, kk, c, bm, dy.dtype, wgrad=False)
        w2t = w.reshape(kk, c).astype(dy.dtype)
        da2, _, _ = _mm(dy.reshape(m, kk), w2t, None, None, False, "none",
                        False, bm, interpret)
        da = da2.reshape(n, ho, wo, c)
        if s_ != 1:
            da = jnp.zeros(x_shape, dy.dtype).at[
                :, ::s_, ::s_, :].set(da)
        return da
    n, ho, wo, kk = dy.shape
    c = w.shape[1]
    h, ww = x_shape[1], x_shape[2]
    if s_ != 1:
        dyd = jnp.zeros((n, (ho - 1) * s_ + 1, (wo - 1) * s_ + 1, kk),
                        dy.dtype).at[:, ::s_, ::s_, :].set(dy)
    else:
        dyd = dy
    # padded length must be H + 2 so the stride-1 valid conv emits H rows
    pr_h = h + 1 - dyd.shape[1]
    pr_w = ww + 1 - dyd.shape[2]
    dyp = jnp.pad(dyd, ((0, 0), (1, pr_h), (1, pr_w), (0, 0)))
    wt = jnp.transpose(w[:, :, ::-1, ::-1], (2, 3, 0, 1)).reshape(
        9, kk, c).astype(dy.dtype)
    bh = block_h or _pick_block_h(h, n, h, ww, kk, c, 1, dy.dtype)
    _enforce_c3_spec(n, h, ww, kk, c, bh, 1, dy.dtype, wgrad=False)
    dx, _, _ = _c3(dyp, wt, None, None, False, "none", False, 1, bh, h, ww,
                   interpret)
    return dx


def conv2d_wgrad(x, dy, w_shape, scale=None, shift=None, act: str = "none",
                 stride: Tuple[int, int] = (1, 1),
                 padding: Tuple[int, int] = (0, 0),
                 block_m: Optional[int] = None,
                 block_h: Optional[int] = None,
                 interpret: Optional[bool] = None):
    """Weight gradient ``a^T @ dy`` per tap, a = act(x*scale+shift)
    recomputed in-kernel from the raw input (prologue remat — the unit
    saves only the pre-BN tensor). Returns dw in OIHW, f32."""
    interpret = _interpret_default() if interpret is None else interpret
    prologue = scale is not None
    k = w_shape[2]
    s_ = stride[0]
    if k == 1:
        xs = x if stride == (1, 1) else x[:, ::s_, ::s_, :]
        n, h, ww, c = xs.shape
        m = n * h * ww
        kk = w_shape[0]
        bm = block_m or _pick_block_m(m, c, kk, x.dtype)
        _enforce_mm_spec(m, c, kk, bm, x.dtype, wgrad=True)
        dw2 = _mm_wgrad(xs.reshape(m, c), dy.reshape(m, kk), scale, shift,
                        prologue, act, bm, interpret)
        return dw2.T.reshape(w_shape)
    n, h, ww, c = x.shape
    kk = w_shape[0]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    ho = (h + 2 - 3) // s_ + 1
    bh = block_h or _pick_block_h(ho, n, h, ww, c, kk, s_, x.dtype)
    _enforce_c3_spec(n, h, ww, c, kk, bh, s_, x.dtype, wgrad=True)
    dw9 = _c3_wgrad(xp, dy, scale, shift, prologue, act, s_, bh, h, ww,
                    interpret)
    return jnp.transpose(dw9.reshape(3, 3, c, kk), (3, 2, 0, 1))


# ---------------------------------------------------------------------------
# Differentiable wrapper: the dgrad/wgrad pair wired through custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d(x, w, stride: Tuple[int, int] = (1, 1),
           padding: Tuple[int, int] = (0, 0)):
    """Differentiable Pallas conv (no prologue): the parity target vs
    ``lax.conv_general_dilated`` autodiff — values, dx, dw."""
    y, _, _ = conv2d_fwd(x, w, stride=stride, padding=padding, stats=False)
    return y


def _conv2d_vjp_fwd(x, w, stride, padding):
    return conv2d(x, w, stride, padding), (x, w)


def _conv2d_vjp_bwd(stride, padding, res, dy):
    x, w = res
    dx = conv2d_dgrad(dy, w, x.shape, stride, padding).astype(x.dtype)
    dw = conv2d_wgrad(x, dy, w.shape, stride=stride,
                      padding=padding).astype(w.dtype)
    return dx, dw


conv2d.defvjp(_conv2d_vjp_fwd, _conv2d_vjp_bwd)


# ---------------------------------------------------------------------------
# Routability + static TPU-constraint enforcement
# ---------------------------------------------------------------------------

def _specs(x_shape, w_shape, stride, dtype, block_m=None, block_h=None):
    from ...analysis.pallas_check import (spec_for_conv_matmul,
                                          spec_for_conv3x3)
    n, h, ww, c = x_shape
    kk, _, kh, _ = w_shape
    s_ = stride[0]
    if kh == 1:
        m = n * ((h + s_ - 1) // s_) * ((ww + s_ - 1) // s_)
        bm = block_m or _pick_block_m(m, c, kk, dtype)
        return [spec_for_conv_matmul(m, c, kk, bm, dtype=dtype),
                spec_for_conv_matmul(m, c, kk, bm, dtype=dtype, wgrad=True)]
    ho = (h + 2 - 3) // s_ + 1
    bh = block_h or _pick_block_h(ho, n, h, ww, c, kk, s_, dtype)
    bh_dg = block_h or _pick_block_h(h, n, h, ww, kk, c, 1, dtype)
    return [spec_for_conv3x3(n, h, ww, c, kk, bh, s_, dtype=dtype),
            spec_for_conv3x3(n, h, ww, c, kk, bh, s_, dtype=dtype,
                             wgrad=True),
            # dgrad runs the fwd kernel at stride 1 with C/K swapped
            spec_for_conv3x3(n, h, ww, kk, c, bh_dg, 1, dtype=dtype)]


def _enforce_mm_spec(m, cin, cout, bm, dtype, wgrad: bool):
    from ...analysis.pallas_check import spec_for_conv_matmul
    _enforce(spec_for_conv_matmul(m, cin, cout, bm, dtype=dtype,
                                  wgrad=wgrad), "ops/_pallas/conv.py")


def _enforce_c3_spec(n, h, w, c, k, bh, stride, dtype, wgrad: bool):
    from ...analysis.pallas_check import spec_for_conv3x3
    _enforce(spec_for_conv3x3(n, h, w, c, k, bh, stride, dtype=dtype,
                              wgrad=wgrad), "ops/_pallas/conv.py")


def supports(x_shape, w_shape, stride=(1, 1), padding=(0, 0),
             dilation=(1, 1), groups: int = 1, dtype=jnp.float32) -> bool:
    """Arithmetic routability check: shape family AND the declared block
    configuration fits the TPU constraints (over-VMEM / non-dividing
    configs fall back to the lax path instead of failing in Mosaic)."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    if groups != 1 or tuple(dilation) != (1, 1):
        return False
    kk, cin_w, kh, kw = w_shape
    if kh != kw or kh not in (1, 3):
        return False
    if x_shape[3] != cin_w:
        return False
    s = tuple(stride)
    if s not in ((1, 1), (2, 2)):
        return False
    if kh == 1 and tuple(padding) != (0, 0):
        return False
    if kh == 3:
        if tuple(padding) != (1, 1):
            return False
        if (x_shape[1] + 2 - 3) // s[0] + 1 < 1:
            return False
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return False
    try:
        from ...analysis.pallas_check import check_kernel_spec
        for spec in _specs(x_shape, w_shape, s, dtype):
            if any(d.severity == "error" for d in check_kernel_spec(spec)):
                return False
    except Exception:
        return False
    return True


# ---------------------------------------------------------------------------
# Autotune registration (device rounds; persists winners in the cache)
# ---------------------------------------------------------------------------

def tune_conv_shapes(shapes=None, dtype=jnp.bfloat16, warmup: int = 1,
                     iters: int = 3):
    """Sweep block candidates for the byte-dominant ResNet conv shapes on
    the attached device and persist winners in the autotune cache (the
    ``_pick_block_*`` selectors consult it before the divisor tables).
    Returns {(kernel, key): winning_block}."""
    import numpy as np
    from .autotune import autotune
    out = {}
    rng = np.random.default_rng(0)
    for kind, n, h, w, cin, cout, s_ in (shapes or RESNET50_TOP3_SHAPES):
        x = jnp.asarray(rng.standard_normal((n, h, w, cin)), dtype)
        k = 1 if kind == "conv1x1" else 3
        wgt = jnp.asarray(rng.standard_normal((cout, cin, k, k)) * 0.05,
                          dtype)
        scale = jnp.ones((cin,), jnp.float32)
        shift = jnp.zeros((cin,), jnp.float32)
        stride = (s_, s_)
        pad = (0, 0) if k == 1 else (1, 1)

        def run(blk, _x=x, _w=wgt, _k=k, _stride=stride, _pad=pad):
            kw = {"block_m": blk} if _k == 1 else {"block_h": blk}
            fn = jax.jit(functools.partial(
                conv2d_fwd, act="relu", stride=_stride, padding=_pad,
                stats=True, **kw))
            return fn(_x, _w, scale, shift)

        if k == 1:
            m = n * ((h + s_ - 1) // s_) * ((w + s_ - 1) // s_)
            kernel, key = "pallas_conv1x1", _mm_key(m, cin, cout, dtype)
            cands = [b for b in _MM_BLOCKS if m % b == 0]
        else:
            ho = (h + 2 - 3) // s_ + 1
            kernel, key = "pallas_conv3x3", _c3_key(n, h, w, cin, cout, s_,
                                                    dtype)
            cands = [b for b in _C3_BLOCKS if ho % b == 0]
        out[(kernel, key)] = autotune(kernel, key, cands, run,
                                      warmup=warmup, iters=iters)
    return out
