"""Radix prefix-sharing KV cache: a token trie over immutable full blocks.

The RadixAttention observation (SGLang, Zheng et al. 2023) applied to
our paged substrate: production traffic is dominated by requests that
share a prompt *prefix* — system prompts, few-shot templates, multi-turn
history — and a private-KV-per-request pool recomputes and re-stores
exactly the same pages over and over. The fix is a trie keyed by block
content: every **full** KV block of a committed prompt (``block_size``
tokens; the ragged tail block stays private) becomes a node whose edge
label is its token tuple, and a new request walks the trie with its own
prompt, attaching copy-on-write to every page it matches — zero prefill
compute, zero new HBM for the shared span; only the suffix is computed
and stored privately.

Ownership is refcounts on :class:`~.paged_cache.BlockAllocator`: each
attached sequence holds one ref per shared block, and the tree holds one
*cache* ref of its own, so pages outlive the request that created them.
``seq_refs`` (live attachments) drives eviction: a node is evictable
only when no live sequence reads it and no device-resident child would
lose its path — LRU over refcount-0 leaves. Eviction does not discard
the KV: the node's block is **spilled once** to the host tier
(:meth:`~.paged_cache.PagedKVCache.snapshot` — one host copy no matter
how many sharers come later) and a future match restores it bitwise into
a fresh block, refcount-aware: one restore re-homes the node for every
current and future sharer.

Write isolation (the COW contract, plan_check rule D005): tree-resident
blocks are *immutable* — the engine's prefill/chunk/decode/verify
scatters must never target a device block the tree holds. The engine
asserts this per dispatch against :meth:`device_block_ids`; the declared
StepPlan carries the same discipline as a ``kv_pages_shared`` read-only
buffer.

Matching is capped at ``prompt_len - 1`` tokens: the engine always
recomputes at least the final prompt token, because the first generated
token needs that position's logits — a fully-cached prompt would have
nothing to forward.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics
from .paged_cache import PagedKVCache

__all__ = ["PrefixCache", "PrefixNode"]


class PrefixNode:
    """One full KV block of some committed prompt prefix.

    ``key`` is the block's token tuple (the trie edge label);
    ``block_id`` is its device page while resident, ``host_kv`` the
    one-copy host spill while evicted. ``seq_refs`` counts live
    sequence attachments; ``last_use`` is the LRU tick.
    """

    __slots__ = ("key", "parent", "children", "block_id", "host_kv",
                 "seq_refs", "last_use", "hits")

    def __init__(self, key: Tuple[int, ...], parent: Optional["PrefixNode"]):
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "PrefixNode"] = {}
        self.block_id: Optional[int] = None
        self.host_kv = None
        self.seq_refs = 0
        self.last_use = 0
        self.hits = 0       # attach events beyond the inserting sequence

    @property
    def on_device(self) -> bool:
        return self.block_id is not None


class PrefixCache:
    """The trie + its ownership/eviction policy over one paged pool."""

    def __init__(self, cache: PagedKVCache,
                 mirror: Optional[PagedKVCache] = None):
        self.cache = cache
        self.bs = cache.block_size
        #: optional drafter pool mirroring the target pool 1:1 by block
        #: id (speculative decoding) — its pages spill/restore alongside
        self.mirror = mirror
        self.root = PrefixNode((), None)
        self._tick = 0
        self._nodes = 0
        # cumulative hit accounting for serving.prefix_hit_rate
        self.hit_tokens = 0
        self.lookup_tokens = 0

    # -- bookkeeping ---------------------------------------------------------

    def _touch(self, node: PrefixNode) -> None:
        self._tick += 1
        node.last_use = self._tick

    def _blocks_of(self, prompt_ids: np.ndarray,
                   limit: Optional[int] = None) -> List[Tuple[int, ...]]:
        """Full-block token tuples of a prompt, capped at ``limit``
        blocks (``None`` = every full block)."""
        ids = np.asarray(prompt_ids).reshape(-1)
        n_full = ids.size // self.bs
        if limit is not None:
            n_full = min(n_full, limit)
        return [tuple(int(t) for t in ids[i * self.bs:(i + 1) * self.bs])
                for i in range(n_full)]

    def device_block_ids(self) -> frozenset:
        """Every device block the tree currently holds — the engine's
        per-dispatch COW write-isolation assert set."""
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n.block_id is not None:
                out.append(n.block_id)
            stack.extend(n.children.values())
        return frozenset(out)

    @property
    def n_nodes(self) -> int:
        return self._nodes

    def n_idle_device_blocks(self) -> int:
        """Device blocks held ONLY as cache (seq_refs == 0) — evictable
        on demand, so they don't count against live pool pressure."""
        idle = 0
        stack = [self.root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n is not self.root and n.on_device and n.seq_refs == 0:
                idle += 1
        return idle

    def hit_rate(self) -> float:
        """Cumulative fraction of looked-up prompt tokens served from
        the tree (the ``serving.prefix_hit_rate`` gauge)."""
        if not self.lookup_tokens:
            return 0.0
        return self.hit_tokens / self.lookup_tokens

    def _gauges(self) -> None:
        metrics.gauge("serving.prefix_hit_rate",
                      "cumulative prompt tokens served from the prefix "
                      "tree / prompt tokens looked up").set(
                          round(self.hit_rate(), 6))
        metrics.gauge("serving.prefix_nodes",
                      "blocks registered in the prefix tree").set(
                          self._nodes)

    # -- match / attach ------------------------------------------------------

    def match(self, prompt_ids: np.ndarray) -> List[PrefixNode]:
        """The longest chain of tree nodes covering full blocks of the
        prompt's first ``prompt_len - 1`` tokens (device- or
        host-resident — attach restores the spilled ones). Pure lookup:
        no refs taken, no LRU advance."""
        ids = np.asarray(prompt_ids).reshape(-1)
        keys = self._blocks_of(ids, limit=max(0, (ids.size - 1) // self.bs))
        chain: List[PrefixNode] = []
        node = self.root
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            chain.append(child)
            node = child
        return chain

    def attach(self, seq_rid: str, chain: Sequence[PrefixNode],
               alloc_fn) -> List[int]:
        """Take one sequence ref on every node of ``chain``, restoring
        host-resident nodes into fresh blocks (``alloc_fn(n) ->
        Optional[List[int]]`` — the engine's evict-aware allocator).
        Returns the chain's device block ids in order. On an allocation
        shortfall the chain is attached only up to the last restorable
        node (the caller prefills the rest — a partial hit, not an
        error)."""
        out: List[int] = []
        for node in chain:
            if not node.on_device:
                got = alloc_fn(1)
                if got is None:
                    break
                self.cache.restore(node.host_kv[0], got)
                if self.mirror is not None and node.host_kv[1] is not None:
                    self.mirror.restore(node.host_kv[1], got)
                node.block_id = got[0]
                node.host_kv = None
                # the restore consumed alloc's refcount-1 grant as the
                # tree's own cache hold
            node.seq_refs += 1
            node.hits += 1
            self.cache.allocator.ref([node.block_id])
            self._touch(node)
            out.append(node.block_id)
        return out

    def account(self, prompt_len: int, hit_len: int) -> None:
        """Record one successful admission's lookup/hit token counts
        (the ``serving.prefix_hit_rate`` input) — called once per
        admitted sequence, never on retried admission attempts."""
        self.lookup_tokens += int(prompt_len)
        self.hit_tokens += int(hit_len)
        self._gauges()

    # -- insert --------------------------------------------------------------

    def insert(self, prompt_ids: np.ndarray, block_ids: Sequence[int],
               filled_tokens: int, have: int = 0) -> List[PrefixNode]:
        """Register the fully-written blocks of a (possibly partially
        prefilled) prompt: block *i* is inserted once its ``block_size``
        tokens are all committed AND ``block_ids[i]`` is the device page
        holding them. ``have`` is the caller's existing chain length
        (attached or previously inserted nodes) — only keys past it are
        processed, making progressive chunked insertion idempotent.

        A newly inserted node takes the tree's cache ref on the block
        (``allocator.ref``) and inherits the inserting sequence's
        attachment (``seq_refs = 1`` — the sequence's original alloc
        ref IS its attachment, so release() is uniform across attached
        and inserted nodes). A key that already exists under a
        *different* block (two cold prefills raced the same prefix)
        stops the insertion — the remainder stays private. Returns the
        NEW nodes only; the caller appends them to its chain."""
        limit = min(int(filled_tokens) // self.bs, len(block_ids))
        keys = self._blocks_of(prompt_ids, limit=limit)
        node = self.root
        for key in keys[:have]:
            node = node.children[key]
        new: List[PrefixNode] = []
        for i in range(have, len(keys)):
            key = keys[i]
            child = node.children.get(key)
            if child is not None:
                if child.block_id != int(block_ids[i]):
                    break       # concurrent duplicate: keep ours private
                node = child
                continue
            child = PrefixNode(key, node)
            child.block_id = int(block_ids[i])
            child.seq_refs = 1
            node.children[key] = child
            self._nodes += 1
            self.cache.allocator.ref([child.block_id])
            self._touch(child)
            new.append(child)
            node = child
        self._gauges()
        return new

    # -- release / evict -----------------------------------------------------

    def release(self, chain: Sequence[PrefixNode]) -> None:
        """Drop one sequence ref per node (the sequence's terminal exit
        or its preemption hand-back). The tree's cache ref keeps the
        page resident until eviction needs it."""
        for node in chain:
            if node.seq_refs < 1:
                raise ValueError(
                    f"release of unattached prefix node {node.key[:4]}...")
            node.seq_refs -= 1
            self.cache.allocator.free([node.block_id])
        self._gauges()

    def _evictable(self) -> List[PrefixNode]:
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if (n is not self.root and n.on_device and n.seq_refs == 0
                    and not any(c.on_device for c in n.children.values())):
                out.append(n)
        return out

    def evict(self, n_blocks: int, spill: bool = True) -> int:
        """Free up to ``n_blocks`` device blocks, LRU-first over
        refcount-0 leaves. A victim that earned at least one re-use
        (``hits > 0``) is snapshotted to the host tier exactly once —
        one host copy no matter how many future sharers restore it; a
        never-re-matched page is simply dropped (a D2H on the
        allocation critical path must be earned). ``spill=False``
        forces the drop path (hard pressure: even host memory refused).
        The device block returns to the free list via the tree's last
        ref. Returns the number of blocks actually freed."""
        freed = 0
        cands: List[PrefixNode] = []
        while freed < n_blocks:
            if not cands:
                # one scan amortizes a batch of evictions; a parent only
                # becomes evictable after its children go, so the list
                # is re-scanned when it runs dry
                cands = sorted(self._evictable(),
                               key=lambda nd: -nd.last_use)
            if not cands:
                break
            victim = cands.pop()
            # retain a node that earned a re-use, or that anchors a
            # (host-resident) subtree the match path still walks
            keep = spill and (victim.hits > 0 or bool(victim.children))
            if keep:
                host = self.cache.snapshot([victim.block_id])
                mhost = (self.mirror.snapshot([victim.block_id])
                         if self.mirror is not None else None)
                victim.host_kv = (host, mhost)
            self.cache.allocator.free([victim.block_id])
            victim.block_id = None
            if not keep:
                self._drop(victim)
            metrics.counter("serving.prefix_evictions",
                            "prefix-tree blocks evicted (spilled or "
                            "dropped)").inc()
            freed += 1
        self._gauges()
        return freed

    def _drop(self, node: PrefixNode) -> None:
        """Remove a node (and its subtree — callers only drop leaves)
        from the trie entirely."""
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
            self._nodes -= 1

    def drop_host_tier(self) -> int:
        """Forget every host-spilled node (frees host memory; future
        matches for those prefixes miss and re-prefill). Returns the
        count dropped."""
        dropped = 0
        stack = [self.root]
        victims = []
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n is not self.root and not n.on_device and not n.children:
                victims.append(n)
        for n in victims:
            self._drop(n)
            dropped += 1
        return dropped

    def assert_consistent(self) -> None:
        """Test hook: every device node's block is allocator-owned with
        refcount >= 1 + seq_refs, and no node is both resident and
        spilled."""
        stack = [self.root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n is self.root:
                continue
            if n.on_device:
                assert n.host_kv is None
                rc = self.cache.allocator.refcount(n.block_id)
                assert rc >= 1 + n.seq_refs, \
                    (n.key, n.block_id, rc, n.seq_refs)
            else:
                assert n.host_kv is not None or n.children
