"""Block-paged KV cache: device page pool + free-list allocator + host spill.

The PagedAttention idea (vLLM, SOSP'23) applied to our stack: instead of
one contiguous ``[B, max_len, KH, D]`` cache per sequence (whose max_len
reservation wastes ~60-80% of KV memory on real traffic), the KV store
is a pool of fixed-size *blocks* — ``[L, num_blocks, block_size, KH, D]``
per k and v — and each sequence owns an ordered block list. Allocation
is a min-id free list (deterministic: the same request schedule always
produces the same block assignment, which the tests pin), fragmentation
is impossible (every block is the same shape), and capacity pressure is
handled by *preempting* a sequence: its blocks are gathered to host
memory (``framework/offload.py``'s host tier — ``pinned_host`` on TPU,
``unpinned_host`` on CPU where the parity tests run), freed, and later
restored bitwise into freshly allocated blocks.

Block 0 is reserved as the **null sink**: padded table entries point at
it, so the bucketed prefill/decode executables can scatter the KV of
padding tokens somewhere harmless instead of branching on raggedness.
Nothing ever reads block 0 through an attention mask — gathered keys at
positions >= the sequence's context length are masked to -inf before the
softmax (``ops.flash_attention.single_query_attention``).

All pool updates run through jitted scatter/gather helpers that donate
the pool (XLA updates the pages in place — the pool is never copied),
at dispatch level between executables — never a transfer inside a loop
body (rule J012).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..fault.injection import fire as _fault_fire
from ..framework.offload import host_memory_kind
from ..observability import metrics

__all__ = ["BlockAllocator", "PagedKVCache", "NULL_BLOCK",
           "OutOfBlocksError", "SpillError"]

# Block id every padded table slot points at (reserved at init).
NULL_BLOCK = 0


class OutOfBlocksError(RuntimeError):
    """The pool cannot satisfy an allocation even after preemption.

    The engine treats this as a **per-request** failure (the sequence that
    needed the block ends FAILED with an F003 Diagnostic); it never
    crosses the engine loop."""


class SpillError(RuntimeError):
    """A host-spill allocation/transfer failed. Surfaced per-request: the
    engine fails the victim sequence (freeing its device blocks) instead
    of crashing the serving loop — host memory pressure costs one
    request's work, not the process."""


class BlockAllocator:
    """Min-id free list over ``num_blocks`` KV blocks (block 0 reserved).

    Lowest-id-first allocation keeps the assignment deterministic under a
    fixed request schedule and re-uses freed blocks immediately (hot
    pages stay hot). ``alloc`` is all-or-nothing: a partial grant would
    leave the caller holding blocks it cannot use.

    Every allocated block carries a **refcount** (the prefix-sharing
    substrate): ``alloc`` grants refcount 1, :meth:`ref` adds an owner
    (a sequence attaching to a shared prefix page, or the radix tree's
    own cache hold), and :meth:`free` drops one owner — the block
    returns to the free list only when its last owner lets go. With no
    sharing in play every refcount stays at 1 and alloc/free behave
    exactly as the pre-refcount allocator (the flag-off bitwise
    contract); over-freeing past zero is still a hard ``double-free``.
    """

    def __init__(self, num_blocks: int, reserved: Sequence[int] = (NULL_BLOCK,)):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the null sink), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._reserved = frozenset(int(r) for r in reserved)
        self._free = sorted(set(range(self.num_blocks)) - self._reserved)
        self._used: set = set()
        self._refs: Dict[int, int] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return len(self._used)

    @property
    def n_shared(self) -> int:
        """Blocks currently held by more than one owner."""
        return sum(1 for r in self._refs.values() if r > 1)

    def refcount(self, i: int) -> int:
        return self._refs.get(int(i), 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n lowest free block ids, or None when fewer than n are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        got, self._free = self._free[:n], self._free[n:]
        self._used.update(got)
        for i in got:
            self._refs[i] = 1
        self._gauges()
        return got

    def ref(self, ids: Sequence[int]) -> None:
        """Add one owner to each allocated block (prefix-share attach)."""
        ids = [int(i) for i in ids]
        for i in ids:
            if i not in self._used:
                raise ValueError(f"ref of unallocated block {i}")
        for i in ids:
            self._refs[i] += 1
        self._gauges()

    def free(self, ids: Sequence[int]) -> None:
        """Drop one owner per block; last-owner blocks return to the
        free list."""
        ids = [int(i) for i in ids]
        for i in ids:
            if i in self._reserved:
                raise ValueError(f"freeing reserved block {i}")
            if i not in self._used:
                raise ValueError(f"double-free of block {i}")
            if ids.count(i) > self._refs[i]:
                raise ValueError(
                    f"double-free of block {i} (repeated past its "
                    f"refcount in one free call)")
        released = []
        for i in ids:
            self._refs[i] -= 1
            if self._refs[i] == 0:
                del self._refs[i]
                self._used.discard(i)
                released.append(i)
        if released:
            self._free = sorted(self._free + released)
        self._gauges()

    def _gauges(self) -> None:
        metrics.gauge("serving.kv_blocks_free",
                      "free KV blocks in the paged pool").set(self.n_free)
        metrics.gauge("serving.kv_blocks_used",
                      "allocated KV blocks in the paged pool").set(self.n_used)
        metrics.gauge("serving.blocks_shared",
                      "KV blocks held by more than one owner").set(
                          self.n_shared)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(pages, ids, vals):
    """pages[:, ids] = vals, pool donated (in-place under XLA)."""
    return pages.at[:, ids].set(vals)


@jax.jit
def _gather_blocks(pages, ids):
    return pages[:, ids]


class PagedKVCache:
    """The device page pool for one model: k/v arrays of shape
    ``[n_layers, num_blocks, block_size, kv_heads, head_dim]``.

    The pool arrays are owned here but *written* by the serving engine's
    prefill/decode executables, which take them as donated arguments and
    return the updated pool — :meth:`swap` re-homes the references. Spill
    and restore move whole per-sequence block lists between the pool and
    the host memory tier.
    """

    def __init__(self, n_layers: int, num_blocks: int, block_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32):
        self.n_layers = int(n_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        shape = (n_layers, num_blocks, block_size, kv_heads, head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self.allocator = BlockAllocator(num_blocks)
        self.host_kind = host_memory_kind()

    @property
    def bytes_per_block(self) -> int:
        return (2 * self.n_layers * self.block_size * self.kv_heads *
                self.head_dim * self.dtype.itemsize)

    def swap(self, k, v) -> None:
        """Adopt the pool arrays an executable returned (the old ones were
        donated into it)."""
        self.k, self.v = k, v

    # -- spill / restore -----------------------------------------------------

    def _to_host(self, x: jax.Array):
        """Commit one gathered KV stripe to the host memory tier
        (``pinned_host``/``unpinned_host`` sharding when the runtime
        exposes one, plain host numpy otherwise)."""
        if self.host_kind is None:
            return np.asarray(x)
        tgt = x.sharding.with_memory_kind(self.host_kind)
        return jax.device_put(x, tgt)

    def spill(self, block_ids: Sequence[int]) -> Tuple:
        """Gather ``block_ids`` to host and free them. Returns the opaque
        host KV pair :meth:`restore` takes; the device blocks are
        reusable immediately after.

        A host allocation/transfer failure raises :class:`SpillError`
        (the blocks stay allocated — the caller owns the cleanup); the
        ``serve.mid_spill`` fire point lets the fault drill kill or
        perturb the process inside the spill window, before the blocks
        are freed."""
        ids = jnp.asarray(list(block_ids), jnp.int32)
        try:
            k_host = self._to_host(_gather_blocks(self.k, ids))
            v_host = self._to_host(_gather_blocks(self.v, ids))
            _fault_fire("serve.mid_spill")
            if self.host_kind is not None:
                # Host commit must complete before the blocks are handed
                # out again — a donated overwrite racing the D2H would
                # tear the copy.
                jax.block_until_ready((k_host, v_host))
        except SpillError:
            raise
        except (RuntimeError, MemoryError, ValueError) as e:
            raise SpillError(
                f"host spill of {len(block_ids)} block(s) failed: {e}"
            ) from e
        self.allocator.free(list(block_ids))
        metrics.counter("serving.kv_spills",
                        "sequence KV spills to host memory").inc()
        return (k_host, v_host)

    def snapshot(self, block_ids: Sequence[int]) -> Tuple:
        """Gather ``block_ids`` to the host tier WITHOUT freeing them —
        the prefix tree's eviction spill (the tree drops its device hold
        separately once the copy is committed) and the drafter pool's
        mirror spill (whose blocks are never allocator-owned). Same
        bitwise round-trip contract as :meth:`spill`."""
        ids = jnp.asarray(list(block_ids), jnp.int32)
        try:
            k_host = self._to_host(_gather_blocks(self.k, ids))
            v_host = self._to_host(_gather_blocks(self.v, ids))
            if self.host_kind is not None:
                jax.block_until_ready((k_host, v_host))
        except (RuntimeError, MemoryError, ValueError) as e:
            raise SpillError(
                f"host snapshot of {len(block_ids)} block(s) failed: {e}"
            ) from e
        return (k_host, v_host)

    def restore(self, host_kv: Tuple, block_ids: Sequence[int]) -> None:
        """Scatter a spilled KV pair into freshly allocated blocks (ids
        may differ from the spilled ones — the block table is rewritten
        by the caller). Bitwise: the round trip is a copy, not a cast."""
        k_host, v_host = host_kv
        ids = jnp.asarray(list(block_ids), jnp.int32)
        if int(ids.shape[0]) != int(k_host.shape[1]):
            raise ValueError(
                f"restore of {k_host.shape[1]} blocks into "
                f"{ids.shape[0]} ids")
        self.k = _scatter_blocks(self.k, ids, jnp.asarray(k_host, self.dtype))
        self.v = _scatter_blocks(self.v, ids, jnp.asarray(v_host, self.dtype))
        metrics.counter("serving.kv_restores",
                        "sequence KV restores from host memory").inc()

    def read_blocks(self, block_ids: Sequence[int]) -> Tuple[np.ndarray,
                                                             np.ndarray]:
        """Host copies of the given blocks (tests / debugging)."""
        ids = jnp.asarray(list(block_ids), jnp.int32)
        return (np.asarray(_gather_blocks(self.k, ids)),
                np.asarray(_gather_blocks(self.v, ids)))
