"""Serving resilience: deadlines, bounded admission, load shedding, and
the exactly-once request journal.

The engine (:mod:`.engine`) is fast; this module is what lets it degrade
instead of dying — the overload half of the story the scheduler already
cites from Orca (OSDI'22) and vLLM (SOSP'23), both of which treat
overload behavior and preemption safety as first-class:

- **Typed rejection** (:class:`Rejected`) — the 429-style answer to an
  over-budget submission. Bounded admission (``max_waiting`` on the
  scheduler, ``max_spilled_bytes`` on the engine) turns "the queue grows
  forever" into an explicit, counted backpressure signal
  (``serving.rejected``).
- **Load shedding** (:class:`ShedPolicy`) — when free KV blocks or the
  rolling p99 decode time cross thresholds, the engine sheds the
  lowest-priority/youngest work (waiting first, then running via the
  existing LIFO preemption machinery) one request per iteration, and in
  ``degrade`` mode additionally shrinks the active decode bucket so the
  surviving requests' per-token latency recovers.
- **Exactly-once journal** (:class:`RequestJournal`) — fsynced JSONL of
  admitted-request state. A submission is journaled before any device
  work; an acknowledgment (``done`` with the output tokens, or a
  terminal ``rejected``/``failed``/``expired``/``shed``) is journaled
  before the response would leave the server. A relaunched engine
  replays exactly the submitted-but-unacknowledged requests — the fault
  drill (``tools/serve_drill.py``) kills the serving process mid-decode
  and mid-spill and asserts zero lost and zero duplicated requests with
  token-exact outputs for every survivor.

Everything here is host-side policy; the device dispatch sequence the
declared StepPlan describes is unchanged, which is why ``lint_graph
--model serving`` keeps passing over the resilient engine.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence as Seq, Set

__all__ = ["Rejected", "ShedPolicy", "RequestJournal", "prompt_hash"]


def prompt_hash(prompt_ids) -> str:
    """Content hash of a prompt's token stream (sha1 over the int32
    bytes, truncated). Journaled with every submission so a relaunched
    engine can (a) verify the replay trace still carries the tokens the
    journal admitted and (b) group replayed requests by shared prefix —
    identical-prompt-prefix requests submitted adjacently re-attach to
    the radix tree's surviving pages instead of re-prefilling cold."""
    import numpy as np
    ids = np.asarray(prompt_ids, np.int32).reshape(-1)
    return hashlib.sha1(ids.tobytes()).hexdigest()[:16]


@dataclass(frozen=True)
class Rejected:
    """Typed admission refusal (the HTTP-429 of the engine): the request
    was never admitted, holds no blocks, and will not produce tokens.
    ``reason`` is machine-readable (``queue_full`` / ``spill_budget``);
    ``detail`` is the human sentence."""

    rid: str
    reason: str
    detail: str = ""

    def __bool__(self) -> bool:  # never truthy-confused with a Sequence
        return False


@dataclass
class ShedPolicy:
    """Overload detection + what to do about it.

    The engine consults :meth:`overloaded` once per scheduler iteration
    with the paged pool's free-block fraction and the rolling p99 of the
    last ``window`` decode-iteration wall times. While overloaded the
    engine (a) pauses fresh admissions, (b) sheds one
    lowest-priority/youngest request per iteration
    (``FCFSScheduler.shed_candidate``), and (c) with ``degrade=True``
    shrinks the active decode bucket one rung (preempting the youngest
    residents through the normal LIFO spill path) so the survivors'
    iteration time drops. In degrade mode only *waiting* work (fresh or
    preempted) is shed — residents are squeezed, never dropped; with
    ``degrade=False`` shedding may drop running work to free blocks.
    """

    min_free_block_frac: float = 0.0       # shed below this free fraction
    max_p99_decode_ms: Optional[float] = None  # shed above this decode p99
    window: int = 64                       # rolling decode-time window
    degrade: bool = False                  # also shrink the decode bucket

    def overloaded(self, free_frac: float,
                   p99_decode_ms: Optional[float]) -> Optional[str]:
        """The reason string when a threshold is crossed, else None."""
        if free_frac < self.min_free_block_frac:
            return (f"free KV blocks {free_frac:.3f} < "
                    f"{self.min_free_block_frac:.3f} of pool")
        if (self.max_p99_decode_ms is not None
                and p99_decode_ms is not None
                and p99_decode_ms > self.max_p99_decode_ms):
            return (f"p99 decode {p99_decode_ms:.2f}ms > "
                    f"{self.max_p99_decode_ms:.2f}ms")
        return None


# ---------------------------------------------------------------------------
# Exactly-once request journal
# ---------------------------------------------------------------------------

#: Journal events that acknowledge a request (the client got an answer —
#: tokens or a terminal refusal). A relaunch must NOT replay these.
ACK_EVENTS = ("done", "rejected", "failed", "expired", "shed")


class RequestJournal:
    """Fsynced JSONL journal of admitted-request state for exactly-once
    serving across process deaths.

    One JSON object per line; every append is flushed **and fsynced**
    before the call returns, mirroring the fault injector's fired-event
    journal — a SIGKILL immediately after an acknowledgment cannot lose
    it. Events:

    - ``{"event": "launch"}`` — one per engine incarnation (restart
      counting);
    - ``{"event": "submitted", "rid", "prompt", "max_new_tokens", ...}``
      — admitted-request state, enough to reconstruct the Request;
    - ``{"event": "done", "rid", "tokens"}`` — the output was committed;
    - ``{"event": "rejected"|"failed"|"expired"|"shed", "rid",
      "reason"}`` — a terminal non-success answer.

    :meth:`pending_rids` is the replay set: submitted (or expected) but
    not acknowledged. :meth:`exactly_once_report` is the drill's verdict.
    """

    def __init__(self, path: str):
        from ..analysis.concurrency_check import make_lock
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # append = write + flush + fsync + in-memory mirror as ONE unit:
        # concurrent ackers (a multi-threaded engine, the churn tests)
        # must never interleave half-lines or reorder an ack against its
        # fsync
        self._mu = make_lock("RequestJournal._mu")
        self._events: List[Dict[str, Any]] = []
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        self._events.append(json.loads(line))
                    except ValueError:
                        # a torn tail line from a mid-append kill: the
                        # event it described was never acknowledged
                        break
        self._f = open(path, "a")

    # -- append side (fsync before return) ----------------------------------

    def append(self, event: str, **payload: Any) -> None:
        rec = {"event": event, **payload}
        with self._mu:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            # serializing the fsync IS the exactly-once contract: the
            # ack must be durable before append returns
            os.fsync(self._f.fileno())  # repo-lint: allow T003
            self._events.append(rec)

    def launch(self) -> None:
        self.append("launch")

    def submitted(self, request) -> None:
        self.append("submitted", rid=request.rid,
                    prompt=[int(t) for t in request.prompt_ids],
                    prompt_sha=prompt_hash(request.prompt_ids),
                    max_new_tokens=int(request.max_new_tokens),
                    eos_token_id=request.eos_token_id,
                    deadline_s=request.deadline_s,
                    priority=int(request.priority))

    def done(self, rid: str, tokens: Seq[int]) -> None:
        self.append("done", rid=rid, tokens=[int(t) for t in tokens])

    def terminal(self, rid: str, outcome: str, reason: str = "") -> None:
        if outcome not in ACK_EVENTS:
            raise ValueError(f"not a terminal outcome: {outcome!r}")
        self.append(outcome, rid=rid, reason=reason)

    def close(self) -> None:
        self._f.close()

    # -- read side -----------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._events)

    @property
    def n_launches(self) -> int:
        return sum(1 for e in self._events if e["event"] == "launch")

    def acknowledged_rids(self) -> Set[str]:
        return {e["rid"] for e in self._events if e["event"] in ACK_EVENTS}

    def ack_outcomes(self) -> Dict[str, str]:
        """rid -> first acknowledged outcome (``done`` or a terminal
        refusal kind) — the exact ack mix the live fleet goodput must
        reproduce at drill end."""
        out: Dict[str, str] = {}
        for e in self._events:
            if e["event"] in ACK_EVENTS and e["rid"] not in out:
                out[e["rid"]] = e["event"]
        return out

    def submitted_rids(self) -> Set[str]:
        return {e["rid"] for e in self._events if e["event"] == "submitted"}

    def pending_rids(self, expected: Optional[Seq[str]] = None) -> List[str]:
        """Rids a relaunched engine must replay: everything in
        ``expected`` (or, without it, everything ever submitted) that was
        never acknowledged — in first-seen order."""
        acked = self.acknowledged_rids()
        if expected is not None:
            return [r for r in expected if r not in acked]
        seen: List[str] = []
        for e in self._events:
            if (e["event"] == "submitted" and e["rid"] not in acked
                    and e["rid"] not in seen):
                seen.append(e["rid"])
        return seen

    def prompt_hashes(self) -> Dict[str, str]:
        """rid -> journaled prompt content hash (first submitted record
        wins) — the replay-integrity and prefix-regrouping input."""
        out: Dict[str, str] = {}
        for e in self._events:
            if e["event"] == "submitted" and "prompt_sha" in e \
                    and e["rid"] not in out:
                out[e["rid"]] = e["prompt_sha"]
        return out

    def done_outputs(self) -> Dict[str, List[int]]:
        """rid -> output tokens of the FIRST done record (duplicates are
        a drill failure surfaced by :meth:`exactly_once_report`)."""
        out: Dict[str, List[int]] = {}
        for e in self._events:
            if e["event"] == "done" and e["rid"] not in out:
                out[e["rid"]] = list(e["tokens"])
        return out

    def exactly_once_report(self, expected_rids: Seq[str]
                            ) -> Dict[str, Any]:
        """The drill verdict: every expected rid acknowledged exactly
        once — ``lost`` (no ack) and ``duplicated`` (>1 ack) must both be
        empty."""
        acks: Dict[str, int] = {}
        for e in self._events:
            if e["event"] in ACK_EVENTS:
                acks[e["rid"]] = acks.get(e["rid"], 0) + 1
        lost = [r for r in expected_rids if r not in acks]
        duplicated = sorted(r for r, n in acks.items() if n > 1)
        return {"expected": len(expected_rids), "acknowledged": len(acks),
                "lost": lost, "duplicated": duplicated,
                "launches": self.n_launches,
                "exactly_once": not lost and not duplicated}
