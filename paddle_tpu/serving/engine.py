"""The serving engine: continuous batching over paged KV on the AOT stack.

Composition of the two load-bearing serving ideas on our machinery:

- **paged KV** (:mod:`.paged_cache`): every sequence's KV lives in
  fixed-size blocks of one device pool, allocated from a deterministic
  free list, spilled to the host memory tier under pressure;
- **continuous batching** (:mod:`.scheduler`): requests join and leave
  the decode batch at token-iteration granularity — the decode
  executable runs every iteration over *whoever is resident*, padded to
  a registered batch-width bucket;
- **bucketed-shape compilation** (:mod:`.buckets`): prefill lengths and
  decode widths are padded to small registered bucket sets, so a ragged
  request trace compiles at most ``len(prefill_buckets) +
  len(decode_buckets)`` executables. Each executable family is watched
  by its own :class:`~paddle_tpu.observability.RecompileSentinel` whose
  threshold *is* the bucket count — O001 stays silent exactly while the
  bucketing works, and fires (through the analysis channel) the moment
  an unregistered signature slips through.

Three throughput tiers compose on top (ISSUE 13; each default-off and
byte-identical when off):

- **radix prefix sharing** (``FLAGS_serve_prefix_cache``,
  :mod:`.prefix_tree`): prompts sharing a full-block prefix attach
  copy-on-write to the same pages via the refcounted allocator; only
  the suffix is prefilled (through the ``extend`` executable), eviction
  is LRU-over-refcount-0 trie leaves with a one-copy host spill tier;
- **chunked prefill** (``FLAGS_serve_chunked_prefill``): long prompts
  prefill in fixed-token chunks interleaved with decode iterations —
  the per-iteration prefill token budget — so a 2k-token prompt no
  longer freezes resident decodes; block tables grow incrementally;
- **speculative decoding** (``FLAGS_serve_speculative``,
  :mod:`.speculative`): a drafter proposes gamma tokens which the
  target verifies in ONE bucketed decode-gamma ``extend`` dispatch
  (greedy accept-prefix rule; the target's own token commits at the
  first mismatch), with accepted-length histograms feeding the
  autotune cache's choice of gamma.

The prefill step runs the model's flash-attention forward on one
bucket-padded prompt and scatters the per-layer K/V into the sequence's
pages; the decode step is a batched single-query pass that gathers each
sequence's pages (``ops.flash_attention.single_query_attention`` masks
the padded tail by context length) and writes the new token's KV in the
same program; the ``extend`` step is the multi-token generalization
(offset-causal over gathered pages) shared by chunked prefill, suffix
prefill after a prefix hit, and speculative verification. Executables
take the page pool **donated** — the pool is updated in place, never
copied — and the whole dispatch sequence is declared as a
:class:`~paddle_tpu.analysis.plan_check.StepPlan` so the
donation-lifetime rules (D001/D002) and the COW write-isolation rule
(D005: a copy-on-write shared buffer is never written or donated)
verify the serving path like every training tier (``lint_graph --model
serving``). At runtime the same isolation is asserted per dispatch:
no scatter ever targets a device block the prefix tree holds.

Works with any ``GPTForCausalLM``-shaped model (``.gpt.wte/wpe/h/ln_f``,
``.logits``); decoding is greedy (argmax), matching ``model.generate``'s
default.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags as _flags
from ..fault.injection import fire as _fault_fire
from ..observability import live as fleet_live
from ..observability import metrics, request_timeline
from ..observability.request_timeline import percentile
from ..observability.step_monitor import RecompileSentinel
from ..ops.flash_attention import flash_attention, single_query_attention
from .buckets import BucketSet, pow2_buckets, pad_axis
from .paged_cache import (NULL_BLOCK, OutOfBlocksError, PagedKVCache,
                          SpillError)
from .prefix_tree import PrefixCache
from .resilience import Rejected, RequestJournal, ShedPolicy
from .scheduler import FCFSScheduler, Request, Sequence, Status
from .speculative import (DEFAULT_GAMMA, ModelDrafter, NGramDrafter,
                          pick_gamma)

__all__ = ["ServingEngine"]


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _multi_query_attention(q, k, v, pos):
    """Offset-causal attention for the ``extend`` step: ``q`` is
    ``[B, L, H, D]`` (L query tokens at absolute positions ``pos``
    [B, L]); ``k``/``v`` are ``[B, Sk, KH, D]`` gathered pages. Query
    ``(b, i)`` attends keys ``j <= pos[b, i]`` — its own KV was
    scattered before the gather, so self-attention is included exactly
    like the decode step's ``lengths = pos + 1`` mask. Same GQA head
    reshape, f32 score accumulation, and masked-row-safe softmax as
    :func:`~paddle_tpu.ops.flash_attention.single_query_attention`
    (numeric agreement with the decode path is what keeps chunked /
    speculative outputs token-exact against ``model.generate``)."""
    b, L, h, d = q.shape
    sk, kh = k.shape[1], k.shape[2]
    g = h // kh
    scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, L, kh, g, d)
    scores = jnp.einsum("blkgd,bskd->blkgs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.arange(sk)[None, None, :] <= pos[:, :, None]   # [B, L, Sk]
    scores = jnp.where(valid[:, :, None, None, :], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.where(jnp.isfinite(scores),
                  jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0)), 0.0)
    probs = (e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True),
                             1e-30)).astype(q.dtype)
    out = jnp.einsum("blkgs,bskd->blkgd", probs, v)
    return out.reshape(b, L, h, d)


class ServingEngine:
    """Paged-KV continuous-batching server over one causal-LM model."""

    def __init__(self, model, *, block_size: int = 8, num_blocks: int = 64,
                 max_batch: int = 8, max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[Seq[int]] = None,
                 decode_buckets: Optional[Seq[int]] = None,
                 detokenizer: Optional[Callable[[np.ndarray], Any]] = None,
                 max_waiting: Optional[int] = None,
                 max_spilled_bytes: Optional[int] = None,
                 shed_policy: Optional[ShedPolicy] = None,
                 journal: Optional[RequestJournal] = None,
                 validate_capacity: bool = True,
                 prefix_cache: Optional[bool] = None,
                 chunked_prefill: Optional[int] = None,
                 speculative: Optional[int] = None,
                 drafter: Optional[Any] = None):
        """Resilience knobs (all default-off, preserving PR-8 behavior):
        ``max_waiting``/``max_spilled_bytes`` bound admission (over-budget
        submissions return a typed :class:`Rejected`), ``shed_policy``
        arms overload load shedding, ``journal`` records admitted-request
        state for exactly-once replay across process deaths, and
        ``validate_capacity=False`` lets a pool smaller than one
        max-length sequence serve anyway — a request that outgrows it
        FAILS (F003) instead of the constructor refusing, which is how
        the drill proves pool exhaustion never crashes the loop.

        Throughput knobs (``None`` reads the matching ``FLAGS_serve_*``
        flag; every one default-off and byte-identical off):
        ``prefix_cache`` arms the radix prefix-sharing tree;
        ``chunked_prefill`` is the per-iteration prefill token budget
        (0 = one-shot prefill); ``speculative`` is the draft depth gamma
        (0 = off, -1 = the autotune cache's accepted-length-derived
        choice) with ``drafter`` an :class:`NGramDrafter` (default) or
        :class:`ModelDrafter`."""
        model.eval()
        cfg = model.cfg
        self.model = model
        self.block_size = int(block_size)
        limit = int(cfg.max_position_embeddings)
        self.max_seq_len = min(int(max_seq_len or limit), limit)
        self.max_blocks_per_seq = _ceil_div(self.max_seq_len, self.block_size)
        if validate_capacity and num_blocks - 1 < self.max_blocks_per_seq:
            raise ValueError(
                f"pool of {num_blocks} blocks cannot hold one max-length "
                f"sequence ({self.max_blocks_per_seq} blocks of "
                f"{self.block_size})")
        self.detokenizer = detokenizer

        # -- bucket sets (the compile budget) --------------------------------
        max_prefill = self.max_blocks_per_seq * self.block_size
        if prefill_buckets is None:
            prefill_buckets = [min(b * self.block_size, max_prefill)
                               for b in pow2_buckets(
                                   1, self.max_blocks_per_seq)]
        for s in prefill_buckets:
            if s % self.block_size or s > max_prefill:
                raise ValueError(
                    f"prefill bucket {s} must be a multiple of "
                    f"block_size={self.block_size} and <= {max_prefill}")
        self.prefill_buckets = BucketSet(prefill_buckets)
        self.decode_buckets = BucketSet(
            decode_buckets if decode_buckets is not None
            else pow2_buckets(1, max_batch))

        # -- throughput tiers (ISSUE 13) -------------------------------------
        self.prefix_on = bool(_flags.flag("serve_prefix_cache")) \
            if prefix_cache is None else bool(prefix_cache)
        chunk = int(_flags.flag("serve_chunked_prefill")) \
            if chunked_prefill is None else int(chunked_prefill)
        # the chunk budget is block-granular (chunk KV scatters whole
        # blocks); a sub-block budget rounds up to one block
        self.chunk_tokens = 0 if chunk <= 0 else max(
            self.block_size, (chunk // self.block_size) * self.block_size)
        spec = int(_flags.flag("serve_speculative")) \
            if speculative is None else int(speculative)
        self.drafter = None
        self.spec_gamma = 0
        self._draft_cache: Optional[PagedKVCache] = None
        if spec != 0:
            self.drafter = drafter if drafter is not None else NGramDrafter()
            t_desc = (f"gpt_l{cfg.num_layers}_h{cfg.hidden_size}"
                      f"_v{cfg.vocab_size}")
            d_desc = self.drafter.kind
            if isinstance(self.drafter, ModelDrafter):
                dcfg = self.drafter.model.cfg
                d_desc = (f"gpt_l{dcfg.num_layers}_h{dcfg.hidden_size}"
                          f"_v{dcfg.vocab_size}")
            self.spec_gamma = spec if spec > 0 else pick_gamma(
                t_desc, d_desc, default=DEFAULT_GAMMA)
            self._spec_desc = (t_desc, d_desc)
        self._accept_lens: List[int] = []
        self.spec_stats = {"iterations": 0, "proposed": 0, "accepted": 0}

        # -- device state ----------------------------------------------------
        act_dtype = model.gpt.wte.weight.dtype
        head_dim = cfg.hidden_size // cfg.num_heads
        self.cache = PagedKVCache(cfg.num_layers, num_blocks,
                                  self.block_size, cfg.kv_heads, head_dim,
                                  dtype=act_dtype)
        if isinstance(self.drafter, ModelDrafter):
            dcfg = self.drafter.model.cfg
            if int(dcfg.vocab_size) != int(cfg.vocab_size):
                raise ValueError(
                    f"drafter vocab {dcfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}")
            self._draft_cache = PagedKVCache(
                dcfg.num_layers, num_blocks, self.block_size,
                dcfg.kv_heads, dcfg.hidden_size // dcfg.num_heads,
                dtype=self.drafter.model.gpt.wte.weight.dtype)
        self.prefix = PrefixCache(self.cache, mirror=self._draft_cache) \
            if self.prefix_on else None
        self.sched = FCFSScheduler(max_batch, max_waiting=max_waiting)
        self._seqs: Dict[str, Sequence] = {}
        self._t0 = time.perf_counter()
        #: scheduler iterations run — the "step index" the live fleet
        #: exporter publishes for a serving worker
        self.n_iterations = 0
        self.peak_blocks_used = 0
        #: peak blocks referenced by live sequences (tree-idle cache
        #: holds excluded — they evict on demand); the fair
        #: pool-pressure comparison across prefix-cache arms
        self.peak_live_blocks = 0

        # -- resilience state ------------------------------------------------
        self.max_spilled_bytes = max_spilled_bytes
        self.shed_policy = shed_policy
        self.journal = journal
        self.rejections: List[Rejected] = []
        self.diagnostics: List[Any] = []     # F003 records, newest last
        self.mode = "healthy"                # healthy | shedding | degraded
        self._spilled_bytes = 0
        self._degraded_width: Optional[int] = None
        self._decode_ms: deque = deque(
            maxlen=shed_policy.window if shed_policy else 64)
        if journal is not None:
            journal.launch()

        # -- compiled steps + their sentinels --------------------------------
        self._prefill_raw = self._make_prefill()
        self._decode_raw = self._make_decode()
        self._prefill_fn = jax.jit(self._prefill_raw, donate_argnums=(1, 2))
        self._decode_fn = jax.jit(self._decode_raw, donate_argnums=(1, 2))
        self._sent_prefill = RecompileSentinel(
            threshold=len(self.prefill_buckets))
        self._sent_decode = RecompileSentinel(
            threshold=len(self.decode_buckets))
        self._chunk_raw = None
        self._chunk_fn = None
        self._sent_chunk = None
        if self.prefix_on or self.chunk_tokens:
            self._chunk_raw = self._make_extend(self.model,
                                                last_only=True)
            self._chunk_fn = jax.jit(self._chunk_raw,
                                     donate_argnums=(1, 2))
            self._sent_chunk = RecompileSentinel(
                threshold=len(self.prefill_buckets))
        self._verify_raw = None
        self._verify_fn = None
        self._sent_verify = None
        if self.spec_gamma:
            self._verify_raw = self._make_extend(self.model,
                                                 last_only=False)
            self._verify_fn = jax.jit(self._verify_raw,
                                      donate_argnums=(1, 2))
            self._sent_verify = RecompileSentinel(
                threshold=len(self.decode_buckets))
        self._draft_decode_fn = None
        self._draft_extend_fn = None
        self._sent_draft = None
        if self._draft_cache is not None:
            self._draft_decode_fn = jax.jit(
                self._make_decode(self.drafter.model),
                donate_argnums=(1, 2))
            self._draft_extend_fn = jax.jit(
                self._make_extend(self.drafter.model, last_only=True),
                donate_argnums=(1, 2))
            self._sent_draft = RecompileSentinel(
                threshold=len(self.decode_buckets) +
                len(self.prefill_buckets))
        self.plan = self._build_plan()
        self._linted = False

    # ------------------------------------------------------------------
    # The bucketed executables
    # ------------------------------------------------------------------

    def _make_prefill(self):
        m = self.model
        bs = self.block_size

        def prefill(ids, k_pages, v_pages, block_ids, n_tokens):
            """ids [1, S] bucket-padded; block_ids [S//bs] (null-padded);
            n_tokens: true prompt length. Writes the prompt KV into the
            pages and returns the first generated token."""
            s = ids.shape[1]
            pos = jnp.arange(s)[None, :]
            x = m.gpt.wte(ids) + m.gpt.wpe(pos)
            for li, blk in enumerate(m.gpt.h):
                xn = blk.ln_1(x)
                q, k, v = blk.attn._project_qkv(xn)
                o = flash_attention(q, k, v, causal=True, training=False)
                kv_shape = (s // bs, bs) + k.shape[2:]
                k_pages = k_pages.at[li, block_ids].set(
                    k[0].reshape(kv_shape).astype(k_pages.dtype))
                v_pages = v_pages.at[li, block_ids].set(
                    v[0].reshape(kv_shape).astype(v_pages.dtype))
                x = x + blk.attn.out_proj(o.reshape(1, s, -1))
                x = x + blk.mlp(blk.ln_2(x))
            hidden = m.gpt.ln_f(x)
            last = jax.lax.dynamic_index_in_dim(hidden, n_tokens - 1,
                                                axis=1, keepdims=True)
            logits = m.logits(last)[0, 0]
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return tok, k_pages, v_pages

        return prefill

    def _make_decode(self, model=None):
        m = model if model is not None else self.model
        bs = self.block_size

        def decode(tokens, k_pages, v_pages, tables, ctx_lens):
            """tokens [B] (each sequence's latest token, not yet in KV);
            tables [B, M] null-padded block tables; ctx_lens [B] tokens
            already cached (0 = inactive pad row, which harmlessly
            writes the null block and produces a discarded output).
            One iteration: write each token's KV at position ctx_len,
            attend over ctx_len+1 keys, return the next token."""
            b = tokens.shape[0]
            mx = tables.shape[1] * bs
            pos = ctx_lens
            x = m.gpt.wte(tokens[:, None]) + m.gpt.wpe(pos[:, None])
            bi = jnp.take_along_axis(tables, (pos // bs)[:, None],
                                     axis=1)[:, 0]
            si = pos % bs
            for li, blk in enumerate(m.gpt.h):
                xn = blk.ln_1(x)
                q, k, v = blk.attn._project_qkv(xn)
                k_pages = k_pages.at[li, bi, si].set(
                    k[:, 0].astype(k_pages.dtype))
                v_pages = v_pages.at[li, bi, si].set(
                    v[:, 0].astype(v_pages.dtype))
                keys = k_pages[li][tables].reshape(b, mx, *k.shape[2:])
                vals = v_pages[li][tables].reshape(b, mx, *v.shape[2:])
                o = single_query_attention(q, keys, vals, lengths=pos + 1)
                x = x + blk.attn.out_proj(o.reshape(b, 1, -1))
                x = x + blk.mlp(blk.ln_2(x))
            hidden = m.gpt.ln_f(x)
            logits = m.logits(hidden)[:, 0]
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return tok, k_pages, v_pages

        return decode

    def _make_extend(self, model, last_only: bool = False):
        """The multi-token paged step: chunk prefill, prefix-hit suffix
        prefill, and speculative verify are all this one program at
        different (B, L) buckets. ``last_only=True`` (the chunk/prefill
        form) projects logits for only each row's final real token —
        the verify form needs the argmax at EVERY position for the
        accept-prefix rule, the chunk form only the next token."""
        m = model
        bs = self.block_size

        def extend(tokens, k_pages, v_pages, tables, ctx_lens, n_real):
            """tokens [B, L]; tables [B, M] null-padded; ctx_lens [B]
            tokens already cached per row; n_real [B] real tokens in
            this dispatch (padded slots scatter into the null block).
            Writes tokens[b, i]'s KV at position ctx_lens[b] + i and
            returns the greedy argmax — [B, L] (every query) or [B]
            (each row's last real query) under ``last_only``."""
            b, L = tokens.shape
            mx = tables.shape[1] * bs
            pos = ctx_lens[:, None] + jnp.arange(L)[None, :]       # [B, L]
            real = jnp.arange(L)[None, :] < n_real[:, None]        # [B, L]
            pos_q = jnp.where(real, pos, 0)
            x = m.gpt.wte(tokens) + m.gpt.wpe(pos_q)
            bi = jnp.take_along_axis(
                tables, jnp.clip(pos // bs, 0, tables.shape[1] - 1),
                axis=1)
            bi = jnp.where(real, bi, NULL_BLOCK)
            si = pos % bs
            for li, blk in enumerate(m.gpt.h):
                xn = blk.ln_1(x)
                q, k, v = blk.attn._project_qkv(xn)
                k_pages = k_pages.at[li, bi, si].set(
                    k.astype(k_pages.dtype))
                v_pages = v_pages.at[li, bi, si].set(
                    v.astype(v_pages.dtype))
                keys = k_pages[li][tables].reshape(b, mx, *k.shape[2:])
                vals = v_pages[li][tables].reshape(b, mx, *v.shape[2:])
                o = _multi_query_attention(q, keys, vals, pos_q)
                x = x + blk.attn.out_proj(o.reshape(b, L, -1))
                x = x + blk.mlp(blk.ln_2(x))
            hidden = m.gpt.ln_f(x)
            if last_only:
                idx = jnp.maximum(n_real - 1, 0)[:, None, None]
                last = jnp.take_along_axis(
                    hidden, jnp.broadcast_to(
                        idx, (b, 1, hidden.shape[-1])), axis=1)
                logits = m.logits(last)[:, 0]
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                logits = m.logits(hidden)
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return toks, k_pages, v_pages

        return extend

    # ------------------------------------------------------------------
    # Declared plan + static analysis
    # ------------------------------------------------------------------

    def _build_plan(self):
        from ..analysis.plan_check import PlanNode, StepPlan
        nodes = [
            PlanNode("serve.prefill", reads=("weights", "prompt_ids"),
                     donates=("kv_pages",),
                     writes=("kv_pages", "next_tokens")),
        ]
        if self.prefix_on or self.chunk_tokens:
            # the extend step READS the copy-on-write shared pages (the
            # prefix tree's immutable blocks) and writes only private
            # pages — rule D005 rejects any plan that writes/donates a
            # buffer listed in flags["cow_shared_buffers"]
            nodes.append(PlanNode(
                "serve.chunk_prefill",
                reads=("weights", "chunk_ids", "block_tables",
                       "kv_pages_shared"),
                donates=("kv_pages",),
                writes=("kv_pages", "next_tokens")))
        if self.spec_gamma:
            nodes.append(PlanNode(
                "serve.draft",
                reads=("draft_weights", "block_tables", "ctx_lens",
                       "draft_kv_pages_shared"),
                donates=("draft_kv_pages",),
                writes=("draft_kv_pages", "draft_tokens")))
            nodes.append(PlanNode(
                "serve.verify",
                reads=("weights", "draft_tokens", "block_tables",
                       "ctx_lens", "kv_pages_shared"),
                donates=("kv_pages",),
                writes=("kv_pages", "next_tokens")))
        nodes += [
            PlanNode("serve.decode",
                     reads=("weights", "block_tables", "ctx_lens"),
                     donates=("kv_pages",),
                     writes=("kv_pages", "next_tokens")),
            PlanNode("serve.spill", reads=("kv_pages",),
                     writes=("host_kv",)),
            PlanNode("serve.restore", reads=("host_kv",),
                     donates=("kv_pages",), writes=("kv_pages",)),
        ]
        flags = {"block_size": self.block_size,
                 "num_blocks": self.cache.num_blocks,
                 "max_batch": self.sched.max_batch,
                 "prefill_buckets": str(self.prefill_buckets.sizes),
                 "decode_buckets": str(self.decode_buckets.sizes),
                 # resilience knobs change scheduling, not dispatch —
                 # declared so the verified plan names the whole config
                 "max_waiting": str(self.sched.max_waiting),
                 "max_spilled_bytes": str(self.max_spilled_bytes),
                 "shed_policy": repr(self.shed_policy),
                 "serve_prefix_cache": self.prefix_on,
                 "serve_chunked_prefill": self.chunk_tokens,
                 "serve_speculative": self.spec_gamma}
        if self.prefix_on:
            flags["cow_shared_buffers"] = \
                "kv_pages_shared,draft_kv_pages_shared"
        return StepPlan(flags=flags, mesh_axes={}, params={}, nodes=nodes)

    def trace_steps(self):
        """Closed jaxprs of the engine's executables at their smallest
        buckets — the ``lint_graph --model serving`` / plan_check
        inputs. Returns ``{name: (closed_jaxpr, donate_argnums)}``;
        ``extend`` (chunk/suffix prefill), ``verify`` (decode-gamma) and
        the drafter pair appear only when the matching tier is armed."""
        s0 = self.prefill_buckets.sizes[0]
        b0 = self.decode_buckets.sizes[0]
        c = self.cache
        m_blocks = self.max_blocks_per_seq
        pages = jax.ShapeDtypeStruct(c.k.shape, c.k.dtype)
        i32 = jnp.int32
        pre = jax.make_jaxpr(self._prefill_raw)(
            jax.ShapeDtypeStruct((1, s0), i32), pages, pages,
            jax.ShapeDtypeStruct((s0 // self.block_size,), i32),
            jax.ShapeDtypeStruct((), i32))
        dec = jax.make_jaxpr(self._decode_raw)(
            jax.ShapeDtypeStruct((b0,), i32), pages, pages,
            jax.ShapeDtypeStruct((b0, m_blocks), i32),
            jax.ShapeDtypeStruct((b0,), i32))
        out = {"prefill": (pre, (1, 2)), "decode": (dec, (1, 2))}
        if self._chunk_raw is not None:
            out["extend"] = (jax.make_jaxpr(self._chunk_raw)(
                jax.ShapeDtypeStruct((1, s0), i32), pages, pages,
                jax.ShapeDtypeStruct((1, m_blocks), i32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((1,), i32)), (1, 2))
        if self._verify_raw is not None:
            L = self.spec_gamma + 1
            out["verify"] = (jax.make_jaxpr(self._verify_raw)(
                jax.ShapeDtypeStruct((b0, L), i32), pages, pages,
                jax.ShapeDtypeStruct((b0, m_blocks), i32),
                jax.ShapeDtypeStruct((b0,), i32),
                jax.ShapeDtypeStruct((b0,), i32)), (1, 2))
        if self._draft_cache is not None:
            dpages = jax.ShapeDtypeStruct(self._draft_cache.k.shape,
                                          self._draft_cache.k.dtype)
            out["draft"] = (jax.make_jaxpr(
                self._make_decode(self.drafter.model))(
                    jax.ShapeDtypeStruct((b0,), i32), dpages, dpages,
                    jax.ShapeDtypeStruct((b0, m_blocks), i32),
                    jax.ShapeDtypeStruct((b0,), i32)), (1, 2))
        return out

    def compile_decode(self):
        """AOT lower+compile the decode executable at its smallest
        bucket — the compiled-HLO verifier's serving input
        (``analysis/hlo_check``). Returns ``(compiled,
        donated_leaves)``: the page pool's two donated buffers must
        realize input/output aliases (X002 — an unaliased pool doubles
        the engine's HBM footprint), and a single-partition decode
        module must compile with zero collectives (X001)."""
        b0 = self.decode_buckets.sizes[0]
        c = self.cache
        pages = jax.ShapeDtypeStruct(c.k.shape, c.k.dtype)
        i32 = jnp.int32
        compiled = self._decode_fn.lower(
            jax.ShapeDtypeStruct((b0,), i32), pages, pages,
            jax.ShapeDtypeStruct((b0, self.max_blocks_per_seq), i32),
            jax.ShapeDtypeStruct((b0,), i32)).compile()
        return compiled, 2

    def compile_extend(self, verify: bool = False):
        """AOT lower+compile the extend executable (chunk signature, or
        the decode-gamma verify signature) for the X pass — same aliasing
        and zero-collective contract as :meth:`compile_decode`."""
        fn = self._verify_fn if verify else self._chunk_fn
        if fn is None:
            raise ValueError("extend executable not armed (enable "
                             "prefix_cache/chunked_prefill/speculative)")
        c = self.cache
        pages = jax.ShapeDtypeStruct(c.k.shape, c.k.dtype)
        i32 = jnp.int32
        if verify:
            b, L = self.decode_buckets.sizes[0], self.spec_gamma + 1
        else:
            b, L = 1, self.prefill_buckets.sizes[0]
        compiled = fn.lower(
            jax.ShapeDtypeStruct((b, L), i32), pages, pages,
            jax.ShapeDtypeStruct((b, self.max_blocks_per_seq), i32),
            jax.ShapeDtypeStruct((b,), i32),
            jax.ShapeDtypeStruct((b,), i32)).compile()
        return compiled, 2

    def _maybe_lint(self) -> None:
        """FLAGS_static_analysis hook: on first dispatch, lint every
        armed step graph, verify the declared plan (one trace feeds
        them), and — final stage — verify the compiled decode module's
        optimized HLO against the plan (X-rules, analysis/hlo_check)."""
        if self._linted:
            return
        self._linted = True
        from ..analysis import hlo_check, jaxpr_lint, plan_check
        if jaxpr_lint.analysis_mode() == "off":
            return
        diags = []
        traced = self.trace_steps()
        for name, (closed, donate) in traced.items():
            diags += jaxpr_lint.lint_jaxpr(closed, donate_argnums=donate,
                                           where=f"serving.{name}")
        diags += plan_check.check_plan(self.plan, traced["decode"][0],
                                       donate_argnums=traced["decode"][1],
                                       where="serving")
        try:
            compiled, donated = self.compile_decode()
        except Exception:
            compiled = None  # first dispatch will surface the error
        if compiled is not None:
            diags += hlo_check.check_hlo(self.plan, compiled,
                                         donated_leaves=donated,
                                         where="serving.decode.hlo")
        if diags:
            jaxpr_lint.emit(diags, where="serving")

    # ------------------------------------------------------------------
    # Allocation, COW isolation, shared-block accounting
    # ------------------------------------------------------------------

    def _alloc(self, n: int) -> Optional[List[int]]:
        """Evict-aware allocation: on a shortfall the prefix tree spills
        LRU refcount-0 leaves to the host tier until the grant fits (or
        nothing is evictable). The flag-off path is exactly
        ``allocator.alloc``."""
        got = self.cache.allocator.alloc(n)
        if got is None and self.prefix is not None:
            # evict with headroom: the per-token alloc(1) pattern would
            # otherwise pay a tree scan per block under pressure
            deficit = max(n - self.cache.allocator.n_free, 4)
            if self.prefix.evict(deficit) > 0:
                got = self.cache.allocator.alloc(n)
        if got is not None:
            self.peak_blocks_used = max(self.peak_blocks_used,
                                        self.cache.allocator.n_used)
        return got

    def _assert_cow(self, write_ids) -> None:
        """The runtime half of rule D005: no dispatch may scatter into a
        device block the prefix tree holds — shared pages are immutable;
        only the private tail is ever written."""
        if self.prefix is None:
            return
        bad = self.prefix.device_block_ids().intersection(
            int(i) for i in write_ids)
        if bad:
            raise AssertionError(
                f"COW write-isolation violated: dispatch would write "
                f"shared prefix blocks {sorted(bad)}")

    def _write_span_ids(self, seq: Sequence, start: int, n: int
                        ) -> List[int]:
        """Block ids covering token positions [start, start+n)."""
        if n <= 0:
            return []
        lo, hi = start // self.block_size, (start + n - 1) // self.block_size
        return seq.block_ids[lo:hi + 1]

    def _private_blocks(self, seq: Sequence) -> int:
        """The prefix-sharing cost model (satellite 2): blocks a
        preemption/shed of this sequence would actually free — its
        refcount-1 private tail, not the shared tree pages."""
        return len(seq.block_ids) - seq.n_shared_blocks

    def _cost_fn(self):
        """Victim-selection cost hook: armed only with the prefix cache
        (the flag-off scheduler order stays bitwise-identical)."""
        return self._private_blocks if self.prefix is not None else None

    def _free_seq_blocks(self, seq: Sequence) -> None:
        """One exit for a sequence's device-block ownership: release the
        tree attachments (the tree's own cache ref keeps shared pages
        resident) and free the private tail."""
        if seq.prefix_nodes:
            self.prefix.release(seq.prefix_nodes)
            seq.prefix_nodes = []
        private = seq.block_ids[seq.n_shared_blocks:]
        if private:
            self.cache.allocator.free(private)
        seq.block_ids = []
        seq.n_shared_blocks = 0

    # ------------------------------------------------------------------
    # Request lifecycle
    # ------------------------------------------------------------------

    def submit(self, request: Request) -> Union[Sequence, Rejected]:
        """Admit one request, or answer with a typed :class:`Rejected`
        (429-style) when the bounded queue or the host-spill budget is
        over capacity. Malformed requests (a total that can never fit
        ``max_seq_len``) still raise — that is a client contract error,
        not transient overload."""
        total = request.prompt_ids.size + request.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request {request.rid!r}: prompt {request.prompt_ids.size} "
                f"+ max_new_tokens {request.max_new_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        # the prompt must fit a registered prefill bucket on its own
        self.prefill_buckets.fit(request.prompt_ids.size)
        metrics.counter("serving.requests", "requests submitted").inc()
        if not self.sched.can_accept():
            return self._reject(
                request, "queue_full",
                f"waiting queue at max_waiting={self.sched.max_waiting}")
        if (self.max_spilled_bytes is not None
                and self._spilled_bytes > self.max_spilled_bytes):
            return self._reject(
                request, "spill_budget",
                f"host spill {self._spilled_bytes}B over budget "
                f"{self.max_spilled_bytes}B")
        seq = Sequence(request)
        seq.t_submit = time.perf_counter()
        self._seqs[request.rid] = seq
        if self.journal is not None:
            self.journal.submitted(request)
        self.sched.submit(seq)
        self._gauges()
        return seq

    def _reject(self, request: Request, reason: str,
                detail: str) -> Rejected:
        rej = Rejected(request.rid, reason, detail)
        self.rejections.append(rej)
        metrics.counter("serving.rejected",
                        "submissions refused by bounded admission").inc()
        if self.journal is not None:
            self.journal.terminal(request.rid, "rejected", reason)
        request_timeline.current().record(
            rid=request.rid, prompt_tokens=request.prompt_ids.size,
            new_tokens=0, phases_ms={}, total_ms=0.0,
            outcome="rejected", error=f"{reason}: {detail}",
            deadline_ms=(None if request.deadline_s is None
                         else request.deadline_s * 1e3))
        return rej

    def _gauges(self) -> None:
        metrics.gauge("serving.queue_depth",
                      "requests waiting for admission").set(
                          len(self.sched.waiting))
        metrics.gauge("serving.running",
                      "sequences resident in the decode batch").set(
                          len(self.sched.running))
        used = self.cache.allocator.n_used
        self.peak_blocks_used = max(self.peak_blocks_used, used)
        live = used - (self.prefix.n_idle_device_blocks()
                       if self.prefix is not None else 0)
        self.peak_live_blocks = max(self.peak_live_blocks, live)
        usable = self.cache.num_blocks - 1
        metrics.gauge("serving.free_block_frac",
                      "free fraction of the usable KV pool (the shed "
                      "policy's admission signal)").set(
                          self.cache.allocator.n_free / usable
                          if usable else 0.0)
        p99 = percentile(list(self._decode_ms), 99)
        if p99 is not None:
            metrics.gauge("serving.decode_p99_ms",
                          "sliding-window decode-iteration p99 (ms, "
                          "the shed policy's latency signal)").set(p99)

    def reset_peaks(self) -> None:
        """Restart the peak-blocks watermarks (bench arms measure the
        steady state, not the warmup)."""
        self.peak_blocks_used = 0
        self.peak_live_blocks = 0
        self._gauges()

    # -- terminal non-success paths (isolation, deadlines, shedding) ---------

    def _cancel(self, seq: Sequence, status: Status, reason: str,
                *, diagnose: bool = False) -> None:
        """The one exit for every non-FINISHED ending: scheduler
        retirement, provable reclamation of device blocks AND host-spill
        buffers, journal acknowledgment, timeline record, counters. The
        allocator-invariant tests pin the zero-leak property."""
        self.sched.retire(seq, status)
        self._free_seq_blocks(seq)
        if seq.host_kv is not None:
            seq.host_kv = None
            seq.host_draft_kv = None
            self._account_spill(-seq.spilled_bytes)
            seq.spilled_bytes = 0
        seq.error = reason
        outcome = status.value
        metrics.counter(f"serving.{outcome}",
                        f"requests ending {outcome}").inc()
        if diagnose:
            self._diagnose_failure(seq, reason)
        if self.journal is not None:
            self.journal.terminal(seq.rid, outcome, reason)
        req = seq.request
        end = time.perf_counter()
        request_timeline.current().record(
            rid=seq.rid, prompt_tokens=seq.prompt_len,
            new_tokens=seq.n_generated,
            phases_ms={k: v * 1e3 for k, v in seq.phase_s.items()},
            total_ms=(end - seq.t_submit) * 1e3,
            ttft_ms=((seq.t_first_token - seq.t_submit) * 1e3
                     if seq.t_first_token is not None else None),
            preemptions=seq.preemptions, outcome=outcome, error=reason,
            deadline_ms=(None if req.deadline_s is None
                         else req.deadline_s * 1e3))
        self._gauges()

    def _diagnose_failure(self, seq: Sequence, reason: str) -> None:
        from ..analysis.jaxpr_lint import Diagnostic, emit
        d = Diagnostic(
            rule="F003", name="serving-request-failed", severity="warning",
            message=f"request {seq.rid!r} failed after "
                    f"{seq.n_generated} token(s): {reason}",
            hint="the failure is isolated to this request; the engine "
                 "loop continues and its blocks were reclaimed",
            where="serving.engine")
        self.diagnostics.append(d)
        # Operational finding — forced warn so it is visible even with
        # FLAGS_static_analysis=off (same contract as F001).
        emit([d], where="serving.engine", mode="warn")

    def _account_spill(self, delta_bytes: int) -> None:
        self._spilled_bytes = max(0, self._spilled_bytes + delta_bytes)
        metrics.gauge("serving.spilled_bytes",
                      "bytes of preempted KV held in the host tier").set(
                          self._spilled_bytes)

    def _expire_deadlines(self) -> None:
        """Cancel every live sequence past its deadline — iteration
        granularity, measured from TRUE submission time (``t_submit`` is
        never rewritten by preemption)."""
        now = time.perf_counter()
        live = list(self.sched.waiting) + list(self.sched.running)
        for seq in live:
            d = seq.request.deadline_s
            if d is not None and now - seq.t_submit > d:
                self._cancel(seq, Status.EXPIRED,
                             f"deadline {d * 1e3:.0f}ms exceeded "
                             f"({(now - seq.t_submit) * 1e3:.0f}ms elapsed)")

    def _apply_shed_policy(self) -> None:
        """One policy consult per iteration: set ``mode``, shed at most
        one request (lowest-priority, then most-private-blocks under the
        prefix cost model, youngest last; waiting first), and in
        degraded mode compute the shrunken decode-bucket cap."""
        pol = self.shed_policy
        if pol is None:
            return
        usable = self.cache.num_blocks - 1
        free_frac = self.cache.allocator.n_free / usable if usable else 0.0
        p99 = percentile(list(self._decode_ms), 99)
        why = pol.overloaded(free_frac, p99)
        if why is None:
            self.mode = "healthy"
            self._degraded_width = None
            return
        self.mode = "degraded" if pol.degrade else "shedding"
        metrics.counter("serving.overload_iterations",
                        "iterations spent in shed/degraded mode").inc()
        # degrade mode preserves residents (they get a smaller bucket);
        # pure shed mode may drop running work to free blocks
        victim = self.sched.shed_candidate(waiting_only=pol.degrade,
                                           cost=self._cost_fn())
        if victim is not None:
            self._cancel(victim, Status.SHED, f"load shed: {why}")
        if pol.degrade and len(self.sched.running) > 1:
            fit = self.decode_buckets.fit(len(self.sched.running))
            smaller = [b for b in self.decode_buckets.sizes if b < fit]
            self._degraded_width = smaller[-1] if smaller else 1

    def _enforce_degraded_width(self) -> None:
        """Degraded mode shrinks the active decode bucket: preempt the
        lowest-priority residents (most private blocks first under the
        prefix cost model — the normal spill path) until the batch fits
        the smaller bucket."""
        cap = self._degraded_width
        if cap is None:
            return
        while len(self.sched.running) > cap:
            victim = self.sched.preempt_victim(cost=self._cost_fn())
            if victim is None:
                break
            try:
                self._preempt(victim)
            except SpillError as e:
                self._cancel(victim, Status.FAILED,
                             f"KV spill failed: {e}", diagnose=True)

    # -- admission (prefill / restore) --------------------------------------

    def _try_admit(self) -> bool:
        if self.mode != "healthy":
            return False            # overload: pause fresh admissions
        seq = self.sched.peek_waiting()
        if seq is None or not self.sched.has_capacity():
            return False
        if seq.status is Status.PREEMPTED:
            return self._admit_restore(seq)
        if self.prefix is not None or self.chunk_tokens:
            return self._admit_extend(seq)
        # -- the flag-off path: byte-identical to the PR-8/9 engine ------
        n_need = _ceil_div(seq.prompt_len, self.block_size)
        ids = self.cache.allocator.alloc(n_need)
        if ids is None:
            if not self.sched.running and self.cache.allocator.n_used == 0:
                # an idle pool that still cannot grant the front request
                # will never be able to: fail it (isolation), keep going
                self._cancel(
                    seq, Status.FAILED,
                    f"needs {n_need} KV block(s), pool has only "
                    f"{self.cache.allocator.n_free}", diagnose=True)
                return True
            return False
        self.sched.admit(seq)
        try:
            self._prefill(seq, ids)
        except Exception as e:  # per-sequence device error: isolate it
            if seq.block_ids:
                # blocks granted this admission that _cancel would miss
                extra = [i for i in ids if i not in seq.block_ids]
            else:
                seq.block_ids = list(ids)
                extra = []
            if extra:
                self.cache.allocator.free(extra)
            self._cancel(seq, Status.FAILED,
                         f"{type(e).__name__}: {e}", diagnose=True)
        return True

    def _admit_restore(self, seq: Sequence) -> bool:
        """Re-admit a preempted sequence: restore its spilled private
        blocks (the shared prefix never left the device — its refs were
        kept through preemption)."""
        n_need = int(seq.host_kv[0].shape[1])
        ids = self._alloc(n_need)
        if ids is None:
            return False
        self.sched.admit(seq)
        try:
            self._restore(seq, ids)
        except Exception as e:
            if not set(ids) <= set(seq.block_ids):
                self.cache.allocator.free(ids)
            self._cancel(seq, Status.FAILED,
                         f"{type(e).__name__}: {e}", diagnose=True)
        return True

    def _admit_extend(self, seq: Sequence) -> bool:
        """Admission with the prefix tree and/or chunked prefill armed:
        attach to the longest cached full-block prefix copy-on-write,
        allocate blocks for the first prefill span (the whole suffix, or
        one chunk under the chunked budget), and either prefill inline
        (one-shot path) or leave the sequence in the chunk pipeline."""
        prompt = seq.request.prompt_ids
        chain: List[Any] = []
        shared_ids: List[int] = []
        if self.prefix is not None and not seq.prefix_nodes:
            chain = self.prefix.match(prompt)
            if chain:
                shared_ids = self.prefix.attach(seq.rid, chain, self._alloc)
                chain = chain[:len(shared_ids)]
        cached = len(shared_ids) * self.block_size
        span = seq.prompt_len - cached
        if self.chunk_tokens:
            span = min(span, self.chunk_tokens)
        n_new = _ceil_div(cached + span, self.block_size) - len(shared_ids)
        ids = self._alloc(n_new)
        if ids is None:
            if chain:
                self.prefix.release(chain)      # clean retry next round
            if not self.sched.running and \
                    self.cache.allocator.n_used == len(
                        self.prefix.device_block_ids()
                        if self.prefix is not None else ()):
                self._cancel(
                    seq, Status.FAILED,
                    f"needs {n_new} KV block(s) beyond the shared prefix, "
                    f"pool has only {self.cache.allocator.n_free}",
                    diagnose=True)
                return True
            return False
        self.sched.admit(seq)
        if self.prefix is not None:
            self.prefix.account(seq.prompt_len, cached)
        if not self.chunk_tokens and cached == 0:
            # cold full prompt, no chunk budget: the one-shot flash
            # prefill path (it inserts the finished blocks into the tree)
            try:
                self._prefill(seq, ids)
            except Exception as e:
                if not seq.block_ids:
                    seq.block_ids = list(ids)
                self._cancel(seq, Status.FAILED,
                             f"{type(e).__name__}: {e}", diagnose=True)
            return True
        seq.add_phase("queue", time.perf_counter() - seq.t_enqueue)
        seq.prefix_nodes = list(chain)
        seq.n_shared_blocks = len(shared_ids)
        seq.block_ids = shared_ids + ids
        seq.block_log.extend(shared_ids + ids)
        seq.ctx_len = cached
        seq.prefill_pos = cached
        if self.chunk_tokens:
            return True             # the chunk pipeline takes it from here
        try:
            self._chunk_prefill(seq, span)
        except Exception as e:
            self._cancel(seq, Status.FAILED,
                         f"{type(e).__name__}: {e}", diagnose=True)
        return True

    def _prefill(self, seq: Sequence, block_ids: List[int]) -> None:
        now = time.perf_counter()
        seq.add_phase("queue", now - seq.t_enqueue)
        bucket = self.prefill_buckets.fit(seq.prompt_len)
        nb_bucket = bucket // self.block_size
        ids = pad_axis(seq.request.prompt_ids[None, :], 1, bucket)
        btab = np.full((nb_bucket,), NULL_BLOCK, np.int32)
        btab[:len(block_ids)] = block_ids
        args = (jnp.asarray(ids, jnp.int32), self.cache.k, self.cache.v,
                jnp.asarray(btab), jnp.asarray(seq.prompt_len, jnp.int32))
        self._maybe_lint()
        self._assert_cow(block_ids)
        self._sent_prefill.observe_tree(
            "serving.prefill", (args[0], args[3], args[4]),
            donate=(1, 2), where="serving.prefill")
        tok, k2, v2 = self._prefill_fn(*args)
        tok = int(tok)  # host sync: honest prefill timing
        self.cache.swap(k2, v2)
        seq.block_ids = list(block_ids)
        seq.block_log.extend(block_ids)
        seq.ctx_len = seq.prompt_len
        seq.prefill_pos = seq.prompt_len
        seq.out_tokens.append(tok)
        seq.t_first_token = time.perf_counter()
        dur = seq.t_first_token - now
        seq.add_phase("prefill", dur)
        metrics.histogram("serving.prefill_ms",
                          "prefill step wall time (ms)").observe(dur * 1e3)
        self._mirror_draft_prefill(seq)
        if self.prefix is not None:
            new_nodes = self.prefix.insert(
                seq.request.prompt_ids, seq.block_ids, seq.prompt_len,
                have=len(seq.prefix_nodes))
            seq.prefix_nodes += new_nodes
            seq.n_shared_blocks = len(seq.prefix_nodes)
        if seq.is_finished_by(tok):
            self._finish(seq)

    def _chunk_prefill(self, seq: Sequence, span: int) -> None:
        """Prefill ``span`` prompt tokens through the ``extend``
        executable starting at ``seq.prefill_pos`` (a block boundary):
        the prefix-hit suffix path and the chunked-prefill path. The
        final span commits the first generated token; every completed
        full block is inserted into the prefix tree as it fills."""
        now = time.perf_counter()
        start = seq.prefill_pos
        L = self.prefill_buckets.fit(span)
        toks = pad_axis(
            seq.request.prompt_ids[None, start:start + span], 1, L)
        table = np.full((1, self.max_blocks_per_seq), NULL_BLOCK, np.int32)
        table[0, :len(seq.block_ids)] = seq.block_ids
        args = (jnp.asarray(toks, jnp.int32), self.cache.k, self.cache.v,
                jnp.asarray(table), jnp.asarray([start], jnp.int32),
                jnp.asarray([span], jnp.int32))
        self._maybe_lint()
        self._assert_cow(self._write_span_ids(seq, start, span))
        self._sent_chunk.observe_tree(
            "serving.extend", (args[0], args[3], args[4], args[5]),
            donate=(1, 2), where="serving.extend")
        out, k2, v2 = self._chunk_fn(*args)
        out = np.asarray(out)   # host sync: honest chunk timing
        self.cache.swap(k2, v2)
        if self._draft_extend_fn is not None:
            dargs = (args[0], self._draft_cache.k, self._draft_cache.v,
                     args[3], args[4], args[5])
            _, dk, dv = self._draft_extend_fn(*dargs)
            self._draft_cache.swap(dk, dv)
            seq.draft_ctx = start + span
        seq.prefill_pos = start + span
        seq.ctx_len = seq.prefill_pos
        if self.prefix is not None:
            new_nodes = self.prefix.insert(
                seq.request.prompt_ids, seq.block_ids, seq.prefill_pos,
                have=len(seq.prefix_nodes))
            seq.prefix_nodes += new_nodes
            seq.n_shared_blocks = len(seq.prefix_nodes)
        dur = time.perf_counter() - now
        seq.add_phase("chunk_prefill", dur)
        if self.chunk_tokens:
            metrics.counter(
                "serving.chunked_prefill_iterations",
                "prefill chunks interleaved with decode").inc()
        metrics.histogram("serving.prefill_ms",
                          "prefill step wall time (ms)").observe(dur * 1e3)
        if seq.prefill_pos >= seq.prompt_len:
            tok = int(out[0])       # last_only: [B] of last-real argmax
            seq.out_tokens.append(tok)
            seq.t_first_token = time.perf_counter()
            if seq.is_finished_by(tok):
                self._finish(seq)

    def _mirror_draft_prefill(self, seq: Sequence) -> None:
        """ModelDrafter: materialize the drafter's prompt KV in the
        mirrored pool (same block table) after a one-shot target
        prefill."""
        if self._draft_extend_fn is None or not seq.block_ids:
            return
        p = seq.prompt_len
        L = self.prefill_buckets.fit(p)
        toks = pad_axis(seq.request.prompt_ids[None, :], 1, L)
        table = np.full((1, self.max_blocks_per_seq), NULL_BLOCK, np.int32)
        table[0, :len(seq.block_ids)] = seq.block_ids
        _, dk, dv = self._draft_extend_fn(
            jnp.asarray(toks, jnp.int32), self._draft_cache.k,
            self._draft_cache.v, jnp.asarray(table),
            jnp.asarray([0], jnp.int32), jnp.asarray([p], jnp.int32))
        self._draft_cache.swap(dk, dv)
        seq.draft_ctx = p

    def _chunk_iteration(self) -> None:
        """The chunked-prefill scheduler slot: at most ``chunk_tokens``
        prompt tokens prefill per engine iteration (the oldest
        mid-prefill resident goes first), interleaved with the decode
        work — a long prompt costs every resident a bounded slice per
        token instead of one unbounded stall."""
        if not self.chunk_tokens:
            return
        for seq in list(self.sched.running):
            if seq.status is not Status.RUNNING or \
                    seq.prefill_pos >= seq.prompt_len:
                continue
            span = min(self.chunk_tokens, seq.prompt_len - seq.prefill_pos)
            needed = _ceil_div(seq.prefill_pos + span, self.block_size)
            ok = True
            while len(seq.block_ids) < needed:
                got = self._alloc(1)
                if got is not None:
                    seq.block_ids.extend(got)
                    seq.block_log.extend(got)
                    continue
                victim = self.sched.preempt_victim(exclude=seq,
                                                   cost=self._cost_fn())
                if victim is None:
                    self._cancel(
                        seq, Status.FAILED,
                        f"needs block {len(seq.block_ids) + 1} of "
                        f"{needed} mid-prefill and there is nothing "
                        "left to preempt — the request outgrew the pool",
                        diagnose=True)
                    ok = False
                    break
                try:
                    self._preempt(victim)
                except SpillError as e:
                    self._cancel(victim, Status.FAILED,
                                 f"KV spill failed: {e}", diagnose=True)
            if ok:
                try:
                    self._chunk_prefill(seq, span)
                except Exception as e:
                    self._cancel(seq, Status.FAILED,
                                 f"{type(e).__name__}: {e}", diagnose=True)
            break                     # one chunk per iteration: the budget

    def _restore(self, seq: Sequence, ids: List[int]) -> None:
        now = time.perf_counter()
        seq.add_phase("queue", now - seq.t_enqueue)
        self.cache.restore(seq.host_kv, ids)
        if self._draft_cache is not None and seq.host_draft_kv is not None:
            self._draft_cache.restore(seq.host_draft_kv, ids)
            seq.host_draft_kv = None
        seq.host_kv = None
        self._account_spill(-seq.spilled_bytes)
        seq.spilled_bytes = 0
        # the shared prefix never left the device — rebuild the table as
        # (pinned shared ids) + (freshly restored private ids)
        seq.block_ids = seq.block_ids[:seq.n_shared_blocks] + list(ids)
        seq.block_log.append(-1)  # spill/restore boundary
        seq.block_log.extend(ids)
        # KV re-materialization substitutes for prefill on resume
        seq.add_phase("prefill", time.perf_counter() - now)

    def _preempt(self, seq: Sequence) -> None:
        self.sched.preempt(seq)
        shared = seq.n_shared_blocks
        private = seq.block_ids[shared:]
        # refcount-aware spill: the shared prefix pages stay pinned on
        # device (this sequence keeps its refs; other sharers and the
        # tree hold them anyway) — only the refcount-1 private tail
        # moves, and it moves exactly once
        if self._draft_cache is not None and private:
            seq.host_draft_kv = self._draft_cache.snapshot(private)
        seq.host_kv = self.cache.spill(private)
        seq.block_ids = seq.block_ids[:shared]
        draft_bytes = (self._draft_cache.bytes_per_block * len(private)
                       if self._draft_cache is not None else 0)
        seq.spilled_bytes = (len(private) * self.cache.bytes_per_block
                             + draft_bytes)
        self._account_spill(seq.spilled_bytes)
        # queue time for the preempted span restarts now; t_submit stays
        # the TRUE arrival so latency + deadlines measure end to end
        seq.t_requeue = time.perf_counter()
        metrics.counter("serving.preemptions",
                        "sequences preempted for KV capacity").inc()

    # -- the decode iteration ------------------------------------------------

    def _decodable(self) -> List[Sequence]:
        """Resident sequences with a committed frontier token (a
        mid-prefill chunked sequence is resident but not yet
        decodable)."""
        return [s for s in self.sched.iteration_batch() if s.out_tokens]

    def _ensure_decode_blocks(self) -> None:
        """Every decodable sequence needs real blocks through position
        ctx_len (+ gamma under speculation) before the next iteration;
        preempt (lowest-priority, most-private-blocks, youngest) to make
        room. Pool exhaustion with nothing left to preempt fails *that*
        sequence (F003) — :class:`OutOfBlocksError` never crosses the
        engine loop."""
        lookahead = self.spec_gamma if self.spec_gamma else 0
        for seq in list(self.sched.running):
            if seq.status is not Status.RUNNING or not seq.out_tokens:
                continue
            needed = (seq.ctx_len + lookahead) // self.block_size + 1
            while len(seq.block_ids) < needed:
                got = self._alloc(1)
                if got is not None:
                    seq.block_ids.extend(got)
                    seq.block_log.extend(got)
                    continue
                victim = self.sched.preempt_victim(exclude=seq,
                                                   cost=self._cost_fn())
                if victim is None:
                    err = OutOfBlocksError(
                        f"sequence {seq.rid!r} needs block "
                        f"{len(seq.block_ids) + 1} of {needed} and there "
                        "is nothing left to preempt — the request "
                        "outgrew the pool")
                    self._cancel(seq, Status.FAILED, str(err),
                                 diagnose=True)
                    break
                try:
                    self._preempt(victim)
                except SpillError as e:
                    self._cancel(victim, Status.FAILED,
                                 f"KV spill failed: {e}", diagnose=True)

    def _decode_iteration(self) -> List[Sequence]:
        batch = self._decodable()
        if not batch:
            return []
        if self.spec_gamma:
            return self._spec_iteration(batch)
        t0 = time.perf_counter()
        width = self.decode_buckets.fit(len(batch))
        m_blocks = self.max_blocks_per_seq
        tokens = np.zeros((width,), np.int32)
        tables = np.full((width, m_blocks), NULL_BLOCK, np.int32)
        lens = np.zeros((width,), np.int32)
        for i, seq in enumerate(batch):
            tokens[i] = seq.out_tokens[-1]
            tables[i, :len(seq.block_ids)] = seq.block_ids
            lens[i] = seq.ctx_len
        args = (jnp.asarray(tokens), self.cache.k, self.cache.v,
                jnp.asarray(tables), jnp.asarray(lens))
        self._maybe_lint()
        for seq in batch:
            self._assert_cow(self._write_span_ids(seq, seq.ctx_len, 1))
        self._sent_decode.observe_tree(
            "serving.decode", (args[0], args[3], args[4]),
            donate=(1, 2), where="serving.decode")
        out, k2, v2 = self._decode_fn(*args)
        out = np.asarray(out)  # host sync per iteration (token commit)
        self.cache.swap(k2, v2)
        # Drill seam: a kill here lands AFTER the iteration's compute but
        # BEFORE any token is committed/acknowledged — the relaunch must
        # replay every in-flight request from scratch, exactly once.
        _fault_fire("serve.mid_decode")
        dur = time.perf_counter() - t0
        self._decode_ms.append(dur * 1e3)
        metrics.histogram("serving.decode_step_ms",
                          "decode iteration wall time (ms)").observe(
                              dur * 1e3)
        finished: List[Sequence] = []
        for i, seq in enumerate(batch):
            seq.add_phase("decode", dur)
            seq.ctx_len += 1
            tok = int(out[i])
            seq.out_tokens.append(tok)
            if seq.is_finished_by(tok):
                finished.append(seq)
        for seq in finished:
            self._finish(seq)
        return finished

    # -- speculative decoding ------------------------------------------------

    def _draft_proposals(self, batch: List[Sequence], width: int,
                         tables: np.ndarray) -> List[List[int]]:
        """Per-sequence proposals (each ≤ gamma tokens). The NGram
        drafter is pure host work; the ModelDrafter runs sequential
        decode dispatches over the mirrored pool — each feed writes the
        fed token's KV at its position, catch-up feeds (committed tokens
        whose drafter KV a rejection invalidated) first."""
        gamma = self.spec_gamma
        if not isinstance(self.drafter, ModelDrafter):
            return [self.drafter.propose(
                list(s.request.prompt_ids) + s.out_tokens, gamma)
                for s in batch]
        hists = [list(int(t) for t in s.request.prompt_ids) + s.out_tokens
                 for s in batch]
        feeds = [h[s.draft_ctx:] for h, s in zip(hists, batch)]
        # feeds ends with the frontier token t0 (KV absent); catch-up
        # length is len(feeds)-1; one proposal lands per feed from t0 on
        steps = max(len(f) - 1 for f in feeds) + gamma
        proposals: List[List[int]] = [[] for _ in batch]
        cur = [list(f) for f in feeds]
        pos0 = [s.draft_ctx for s in batch]
        for t in range(steps):
            toks = np.zeros((width,), np.int32)
            ctxs = np.zeros((width,), np.int32)
            for i, seq in enumerate(batch):
                hi = min(t, len(cur[i]) - 1)
                toks[i] = cur[i][hi] if t < len(cur[i]) else cur[i][-1]
                ctxs[i] = min(pos0[i] + t, seq.ctx_len + gamma)
            dargs = (jnp.asarray(toks), jnp.asarray(tables),
                     jnp.asarray(ctxs))
            if t == 0:
                self._sent_draft.observe_tree(
                    "serving.draft", dargs, donate=(1, 2),
                    where="serving.draft")
            out, dk, dv = self._draft_decode_fn(
                dargs[0], self._draft_cache.k,
                self._draft_cache.v, dargs[1], dargs[2])
            self._draft_cache.swap(dk, dv)
            out = np.asarray(out)
            for i in range(len(batch)):
                catchup = len(feeds[i]) - 1
                if t >= catchup and len(proposals[i]) < gamma:
                    proposals[i].append(int(out[i]))
                    cur[i].append(int(out[i]))
        return proposals

    def _spec_iteration(self, batch: List[Sequence]) -> List[Sequence]:
        """One speculative iteration: draft gamma proposals per resident
        sequence, verify the whole batch in ONE decode-gamma ``extend``
        dispatch, and commit each row's accepted prefix plus the
        target's own token at the first mismatch (1..gamma+1 tokens) —
        exactly the target's greedy stream, drafts or no drafts."""
        gamma = self.spec_gamma
        L = gamma + 1
        width = self.decode_buckets.fit(len(batch))
        m_blocks = self.max_blocks_per_seq
        tables = np.full((width, m_blocks), NULL_BLOCK, np.int32)
        for i, seq in enumerate(batch):
            tables[i, :len(seq.block_ids)] = seq.block_ids
        t0 = time.perf_counter()
        proposals = self._draft_proposals(batch, width, tables)
        t_draft = time.perf_counter() - t0
        tokens = np.zeros((width, L), np.int32)
        lens = np.zeros((width,), np.int32)
        n_real = np.zeros((width,), np.int32)
        for i, seq in enumerate(batch):
            fed = [seq.out_tokens[-1]] + proposals[i]
            tokens[i, :len(fed)] = fed
            lens[i] = seq.ctx_len
            n_real[i] = len(fed)
        args = (jnp.asarray(tokens), self.cache.k, self.cache.v,
                jnp.asarray(tables), jnp.asarray(lens),
                jnp.asarray(n_real))
        self._maybe_lint()
        for i, seq in enumerate(batch):
            self._assert_cow(self._write_span_ids(seq, seq.ctx_len,
                                                  int(n_real[i])))
        self._sent_verify.observe_tree(
            "serving.verify", (args[0], args[3], args[4], args[5]),
            donate=(1, 2), where="serving.verify")
        out, k2, v2 = self._verify_fn(*args)
        out = np.asarray(out)
        self.cache.swap(k2, v2)
        _fault_fire("serve.mid_decode")
        dur = time.perf_counter() - t0
        t_verify = dur - t_draft
        self._decode_ms.append(dur * 1e3)
        metrics.histogram("serving.decode_step_ms",
                          "decode iteration wall time (ms)").observe(
                              dur * 1e3)
        self.spec_stats["iterations"] += 1
        finished: List[Sequence] = []
        for i, seq in enumerate(batch):
            seq.add_phase("draft", t_draft)
            seq.add_phase("verify", t_verify)
            props = proposals[i]
            o = out[i]
            accepted = 0
            while accepted < len(props) and \
                    props[accepted] == int(o[accepted]):
                accepted += 1
            committed = [int(props[j]) for j in range(accepted)]
            committed.append(int(o[accepted]))
            self.spec_stats["proposed"] += len(props)
            self.spec_stats["accepted"] += accepted
            self._accept_lens.append(accepted)
            metrics.histogram(
                "serving.spec_accept_len",
                "draft tokens accepted per speculative iteration"
            ).observe(accepted)
            ctx0 = seq.ctx_len
            done = False
            kept = 0
            for tok in committed:
                seq.out_tokens.append(tok)
                seq.ctx_len += 1
                kept += 1
                if seq.is_finished_by(tok):
                    done = True
                    break
            if isinstance(self.drafter, ModelDrafter):
                # drafter KV is valid through the accepted prefix it
                # fed (t0 + the accepted proposals it chained); the
                # fallback token's KV is next round's catch-up feed
                seq.draft_ctx = min(ctx0 + 1 + min(accepted, gamma - 1)
                                    if gamma > 1 else ctx0 + 1,
                                    seq.ctx_len)
            if done:
                finished.append(seq)
        for seq in finished:
            self._finish(seq)
        return finished

    def record_spec_tuning(self) -> Optional[int]:
        """Persist the accepted-length-derived gamma for this target/
        drafter pair into the kernel autotune cache (consumed by
        ``FLAGS_serve_speculative=-1``). Returns the stored gamma."""
        if not self.spec_gamma or not self._accept_lens:
            return None
        from .speculative import tune_gamma
        return tune_gamma(self._spec_desc[0], self._spec_desc[1],
                          self._accept_lens)

    def _finish(self, seq: Sequence) -> None:
        t0 = time.perf_counter()
        self.sched.finish(seq)
        self._free_seq_blocks(seq)
        out = seq.full_output()
        seq.output = out
        # Acknowledge BEFORE detokenize/record: once the journal holds the
        # done record (fsynced), a relaunch will not replay this request.
        if self.journal is not None:
            self.journal.done(seq.rid, seq.out_tokens)
        if self.detokenizer is not None:
            seq.text = self.detokenizer(out)
        end = time.perf_counter()
        seq.add_phase("detokenize", end - t0)
        total_ms = (end - seq.t_submit) * 1e3
        ttft_ms = ((seq.t_first_token - seq.t_submit) * 1e3
                   if seq.t_first_token is not None else None)
        request_timeline.current().record(
            rid=seq.rid, prompt_tokens=seq.prompt_len,
            new_tokens=seq.n_generated,
            phases_ms={k: v * 1e3 for k, v in seq.phase_s.items()},
            total_ms=total_ms, ttft_ms=ttft_ms,
            preemptions=seq.preemptions, outcome="ok",
            deadline_ms=(None if seq.request.deadline_s is None
                         else seq.request.deadline_s * 1e3))

    # ------------------------------------------------------------------
    # Driving loop
    # ------------------------------------------------------------------

    def step(self) -> List[Sequence]:
        """One scheduler iteration: expire deadlines, consult the shed
        policy, admit whatever fits (prefill / restore at token
        granularity), run one prefill chunk under the chunked budget,
        top up decode blocks (preempting under pressure), run one decode
        iteration. Returns every sequence that reached a terminal state
        this iteration — FINISHED, and also EXPIRED / SHED / FAILED
        retirements."""
        n0 = len(self.sched.finished)
        self._expire_deadlines()
        self._apply_shed_policy()
        self._enforce_degraded_width()
        while self._try_admit():
            pass
        self._chunk_iteration()
        self._ensure_decode_blocks()
        self._decode_iteration()
        self._gauges()
        self.n_iterations += 1
        fleet_live.note_progress(self.n_iterations)
        return self.sched.finished[n0:]

    def serve(self, requests: Seq[Request],
              respect_arrivals: bool = False
              ) -> Dict[str, Union[Sequence, Rejected]]:
        """Drive the full trace to completion; returns rid -> Sequence
        (with ``.output`` / ``.text`` — check ``.status`` for the
        EXPIRED/SHED/FAILED endings) or the :class:`Rejected` answer for
        requests bounded admission refused. ``respect_arrivals`` replays
        each request's ``arrival_s`` offset instead of submitting
        everything up front."""
        order = sorted(requests, key=lambda r: r.arrival_s) \
            if respect_arrivals else list(requests)
        t0 = time.perf_counter()
        idx = 0
        done: Dict[str, Union[Sequence, Rejected]] = {}
        while idx < len(order) or self.sched.n_pending:
            now = time.perf_counter() - t0
            while idx < len(order) and (
                    not respect_arrivals or order[idx].arrival_s <= now):
                res = self.submit(order[idx])
                if isinstance(res, Rejected):
                    done[res.rid] = res
                idx += 1
            if not self.sched.n_pending:
                if idx < len(order) and respect_arrivals:
                    time.sleep(
                        max(0.0, order[idx].arrival_s -
                            (time.perf_counter() - t0)))
                continue
            for seq in self.step():
                done[seq.rid] = seq
        self.sched.assert_idle()
        return done

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    def compile_report(self) -> Dict[str, Any]:
        """Distinct executable signatures dispatched vs the bucket
        budget — the '≤ n_buckets compilations, O001 silent' check."""
        n_pre = len(self._sent_prefill._seen.get("serving.prefill", ()))
        n_dec = len(self._sent_decode._seen.get("serving.decode", ()))
        n_ext = (len(self._sent_chunk._seen.get("serving.extend", ()))
                 if self._sent_chunk is not None else 0)
        n_ver = (len(self._sent_verify._seen.get("serving.verify", ()))
                 if self._sent_verify is not None else 0)
        ext_budget = (self._sent_chunk.threshold
                      if self._sent_chunk is not None else 0)
        ver_budget = (self._sent_verify.threshold
                      if self._sent_verify is not None else 0)
        return {
            "prefill_signatures": n_pre,
            "decode_signatures": n_dec,
            "extend_signatures": n_ext,
            "verify_signatures": n_ver,
            "budget": (len(self.prefill_buckets) +
                       len(self.decode_buckets) + ext_budget +
                       ver_budget),
            "prefill_buckets": self.prefill_buckets.sizes,
            "decode_buckets": self.decode_buckets.sizes,
            "within_budget": (n_pre <= len(self.prefill_buckets) and
                              n_dec <= len(self.decode_buckets) and
                              n_ext <= ext_budget and
                              n_ver <= ver_budget),
            "o001_fired": bool(
                self._sent_prefill.diagnostics or
                self._sent_decode.diagnostics or
                (self._sent_chunk is not None and
                 self._sent_chunk.diagnostics) or
                (self._sent_verify is not None and
                 self._sent_verify.diagnostics) or
                (self._sent_draft is not None and
                 self._sent_draft.diagnostics)),
        }

    def prefix_report(self) -> Dict[str, Any]:
        """Prefix-sharing effectiveness: hit rate, live tree size, and
        the pool-pressure headline (peak blocks in use)."""
        rep = {
            "enabled": self.prefix is not None,
            "peak_blocks_used": self.peak_blocks_used,
            "peak_live_blocks": self.peak_live_blocks,
            "blocks_shared_now": self.cache.allocator.n_shared,
        }
        if self.prefix is not None:
            rep.update({
                "hit_rate": round(self.prefix.hit_rate(), 4),
                "hit_tokens": self.prefix.hit_tokens,
                "lookup_tokens": self.prefix.lookup_tokens,
                "tree_nodes": self.prefix.n_nodes,
                "device_blocks_held": len(self.prefix.device_block_ids()),
            })
        return rep

    def spec_report(self) -> Dict[str, Any]:
        """Speculative-decoding effectiveness: acceptance and the mean
        committed tokens per verify dispatch."""
        it = self.spec_stats["iterations"]
        prop = self.spec_stats["proposed"]
        acc = self.spec_stats["accepted"]
        rows = len(self._accept_lens)   # per-sequence verify samples
        return {
            "enabled": bool(self.spec_gamma),
            "gamma": self.spec_gamma,
            "drafter": getattr(self.drafter, "kind", None),
            "iterations": it,
            "proposed": prop,
            "accepted": acc,
            "accept_rate": round(acc / prop, 4) if prop else 0.0,
            "mean_accept_len": round(acc / rows, 4) if rows else 0.0,
            "tokens_per_verify": round((acc + rows) / rows, 4)
            if rows else 0.0,
        }
