"""The serving engine: continuous batching over paged KV on the AOT stack.

Composition of the two load-bearing serving ideas on our machinery:

- **paged KV** (:mod:`.paged_cache`): every sequence's KV lives in
  fixed-size blocks of one device pool, allocated from a deterministic
  free list, spilled to the host memory tier under pressure;
- **continuous batching** (:mod:`.scheduler`): requests join and leave
  the decode batch at token-iteration granularity — the decode
  executable runs every iteration over *whoever is resident*, padded to
  a registered batch-width bucket;
- **bucketed-shape compilation** (:mod:`.buckets`): prefill lengths and
  decode widths are padded to small registered bucket sets, so a ragged
  request trace compiles at most ``len(prefill_buckets) +
  len(decode_buckets)`` executables. Each executable family is watched
  by its own :class:`~paddle_tpu.observability.RecompileSentinel` whose
  threshold *is* the bucket count — O001 stays silent exactly while the
  bucketing works, and fires (through the analysis channel) the moment
  an unregistered signature slips through.

The prefill step runs the model's flash-attention forward on one
bucket-padded prompt and scatters the per-layer K/V into the sequence's
pages; the decode step is a batched single-query pass that gathers each
sequence's pages (``ops.flash_attention.single_query_attention`` masks
the padded tail by context length) and writes the new token's KV in the
same program. Both executables take the page pool **donated** — the pool
is updated in place, never copied — and the whole dispatch sequence is
declared as a :class:`~paddle_tpu.analysis.plan_check.StepPlan` so the
donation-lifetime rules (D001/D002) and the sharding-flow rules verify
the serving path like every training tier (``lint_graph --model
serving``).

Works with any ``GPTForCausalLM``-shaped model (``.gpt.wte/wpe/h/ln_f``,
``.logits``); decoding is greedy (argmax), matching ``model.generate``'s
default.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..fault.injection import fire as _fault_fire
from ..observability import metrics, request_timeline
from ..observability.request_timeline import percentile
from ..observability.step_monitor import RecompileSentinel
from ..ops.flash_attention import flash_attention, single_query_attention
from .buckets import BucketSet, pow2_buckets, pad_axis
from .paged_cache import (NULL_BLOCK, OutOfBlocksError, PagedKVCache,
                          SpillError)
from .resilience import Rejected, RequestJournal, ShedPolicy
from .scheduler import FCFSScheduler, Request, Sequence, Status

__all__ = ["ServingEngine"]


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class ServingEngine:
    """Paged-KV continuous-batching server over one causal-LM model."""

    def __init__(self, model, *, block_size: int = 8, num_blocks: int = 64,
                 max_batch: int = 8, max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[Seq[int]] = None,
                 decode_buckets: Optional[Seq[int]] = None,
                 detokenizer: Optional[Callable[[np.ndarray], Any]] = None,
                 max_waiting: Optional[int] = None,
                 max_spilled_bytes: Optional[int] = None,
                 shed_policy: Optional[ShedPolicy] = None,
                 journal: Optional[RequestJournal] = None,
                 validate_capacity: bool = True):
        """Resilience knobs (all default-off, preserving PR-8 behavior):
        ``max_waiting``/``max_spilled_bytes`` bound admission (over-budget
        submissions return a typed :class:`Rejected`), ``shed_policy``
        arms overload load shedding, ``journal`` records admitted-request
        state for exactly-once replay across process deaths, and
        ``validate_capacity=False`` lets a pool smaller than one
        max-length sequence serve anyway — a request that outgrows it
        FAILS (F003) instead of the constructor refusing, which is how
        the drill proves pool exhaustion never crashes the loop."""
        model.eval()
        cfg = model.cfg
        self.model = model
        self.block_size = int(block_size)
        limit = int(cfg.max_position_embeddings)
        self.max_seq_len = min(int(max_seq_len or limit), limit)
        self.max_blocks_per_seq = _ceil_div(self.max_seq_len, self.block_size)
        if validate_capacity and num_blocks - 1 < self.max_blocks_per_seq:
            raise ValueError(
                f"pool of {num_blocks} blocks cannot hold one max-length "
                f"sequence ({self.max_blocks_per_seq} blocks of "
                f"{self.block_size})")
        self.detokenizer = detokenizer

        # -- bucket sets (the compile budget) --------------------------------
        max_prefill = self.max_blocks_per_seq * self.block_size
        if prefill_buckets is None:
            prefill_buckets = [min(b * self.block_size, max_prefill)
                               for b in pow2_buckets(
                                   1, self.max_blocks_per_seq)]
        for s in prefill_buckets:
            if s % self.block_size or s > max_prefill:
                raise ValueError(
                    f"prefill bucket {s} must be a multiple of "
                    f"block_size={self.block_size} and <= {max_prefill}")
        self.prefill_buckets = BucketSet(prefill_buckets)
        self.decode_buckets = BucketSet(
            decode_buckets if decode_buckets is not None
            else pow2_buckets(1, max_batch))

        # -- device state ----------------------------------------------------
        act_dtype = model.gpt.wte.weight.dtype
        head_dim = cfg.hidden_size // cfg.num_heads
        self.cache = PagedKVCache(cfg.num_layers, num_blocks,
                                  self.block_size, cfg.kv_heads, head_dim,
                                  dtype=act_dtype)
        self.sched = FCFSScheduler(max_batch, max_waiting=max_waiting)
        self._seqs: Dict[str, Sequence] = {}
        self._t0 = time.perf_counter()

        # -- resilience state ------------------------------------------------
        self.max_spilled_bytes = max_spilled_bytes
        self.shed_policy = shed_policy
        self.journal = journal
        self.rejections: List[Rejected] = []
        self.diagnostics: List[Any] = []     # F003 records, newest last
        self.mode = "healthy"                # healthy | shedding | degraded
        self._spilled_bytes = 0
        self._degraded_width: Optional[int] = None
        self._decode_ms: deque = deque(
            maxlen=shed_policy.window if shed_policy else 64)
        if journal is not None:
            journal.launch()

        # -- compiled steps + their sentinels --------------------------------
        self._prefill_raw = self._make_prefill()
        self._decode_raw = self._make_decode()
        self._prefill_fn = jax.jit(self._prefill_raw, donate_argnums=(1, 2))
        self._decode_fn = jax.jit(self._decode_raw, donate_argnums=(1, 2))
        self._sent_prefill = RecompileSentinel(
            threshold=len(self.prefill_buckets))
        self._sent_decode = RecompileSentinel(
            threshold=len(self.decode_buckets))
        self.plan = self._build_plan()
        self._linted = False

    # ------------------------------------------------------------------
    # The two bucketed executables
    # ------------------------------------------------------------------

    def _make_prefill(self):
        m = self.model
        bs = self.block_size

        def prefill(ids, k_pages, v_pages, block_ids, n_tokens):
            """ids [1, S] bucket-padded; block_ids [S//bs] (null-padded);
            n_tokens: true prompt length. Writes the prompt KV into the
            pages and returns the first generated token."""
            s = ids.shape[1]
            pos = jnp.arange(s)[None, :]
            x = m.gpt.wte(ids) + m.gpt.wpe(pos)
            for li, blk in enumerate(m.gpt.h):
                xn = blk.ln_1(x)
                q, k, v = blk.attn._project_qkv(xn)
                o = flash_attention(q, k, v, causal=True, training=False)
                kv_shape = (s // bs, bs) + k.shape[2:]
                k_pages = k_pages.at[li, block_ids].set(
                    k[0].reshape(kv_shape).astype(k_pages.dtype))
                v_pages = v_pages.at[li, block_ids].set(
                    v[0].reshape(kv_shape).astype(v_pages.dtype))
                x = x + blk.attn.out_proj(o.reshape(1, s, -1))
                x = x + blk.mlp(blk.ln_2(x))
            hidden = m.gpt.ln_f(x)
            last = jax.lax.dynamic_index_in_dim(hidden, n_tokens - 1,
                                                axis=1, keepdims=True)
            logits = m.logits(last)[0, 0]
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return tok, k_pages, v_pages

        return prefill

    def _make_decode(self):
        m = self.model
        bs = self.block_size

        def decode(tokens, k_pages, v_pages, tables, ctx_lens):
            """tokens [B] (each sequence's latest token, not yet in KV);
            tables [B, M] null-padded block tables; ctx_lens [B] tokens
            already cached (0 = inactive pad row, which harmlessly
            writes the null block and produces a discarded output).
            One iteration: write each token's KV at position ctx_len,
            attend over ctx_len+1 keys, return the next token."""
            b = tokens.shape[0]
            mx = tables.shape[1] * bs
            pos = ctx_lens
            x = m.gpt.wte(tokens[:, None]) + m.gpt.wpe(pos[:, None])
            bi = jnp.take_along_axis(tables, (pos // bs)[:, None],
                                     axis=1)[:, 0]
            si = pos % bs
            for li, blk in enumerate(m.gpt.h):
                xn = blk.ln_1(x)
                q, k, v = blk.attn._project_qkv(xn)
                k_pages = k_pages.at[li, bi, si].set(
                    k[:, 0].astype(k_pages.dtype))
                v_pages = v_pages.at[li, bi, si].set(
                    v[:, 0].astype(v_pages.dtype))
                keys = k_pages[li][tables].reshape(b, mx, *k.shape[2:])
                vals = v_pages[li][tables].reshape(b, mx, *v.shape[2:])
                o = single_query_attention(q, keys, vals, lengths=pos + 1)
                x = x + blk.attn.out_proj(o.reshape(b, 1, -1))
                x = x + blk.mlp(blk.ln_2(x))
            hidden = m.gpt.ln_f(x)
            logits = m.logits(hidden)[:, 0]
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return tok, k_pages, v_pages

        return decode

    # ------------------------------------------------------------------
    # Declared plan + static analysis
    # ------------------------------------------------------------------

    def _build_plan(self):
        from ..analysis.plan_check import PlanNode, StepPlan
        nodes = [
            PlanNode("serve.prefill", reads=("weights", "prompt_ids"),
                     donates=("kv_pages",),
                     writes=("kv_pages", "next_tokens")),
            PlanNode("serve.decode",
                     reads=("weights", "block_tables", "ctx_lens"),
                     donates=("kv_pages",),
                     writes=("kv_pages", "next_tokens")),
            PlanNode("serve.spill", reads=("kv_pages",),
                     writes=("host_kv",)),
            PlanNode("serve.restore", reads=("host_kv",),
                     donates=("kv_pages",), writes=("kv_pages",)),
        ]
        return StepPlan(
            flags={"block_size": self.block_size,
                   "num_blocks": self.cache.num_blocks,
                   "max_batch": self.sched.max_batch,
                   "prefill_buckets": str(self.prefill_buckets.sizes),
                   "decode_buckets": str(self.decode_buckets.sizes),
                   # resilience knobs change scheduling, not dispatch —
                   # declared so the verified plan names the whole config
                   "max_waiting": str(self.sched.max_waiting),
                   "max_spilled_bytes": str(self.max_spilled_bytes),
                   "shed_policy": repr(self.shed_policy)},
            mesh_axes={}, params={}, nodes=nodes)

    def trace_steps(self):
        """Closed jaxprs of the two executables at their smallest buckets
        — the ``lint_graph --model serving`` / plan_check inputs. Returns
        ``{name: (closed_jaxpr, donate_argnums)}``."""
        s0 = self.prefill_buckets.sizes[0]
        b0 = self.decode_buckets.sizes[0]
        c = self.cache
        pages = jax.ShapeDtypeStruct(c.k.shape, c.k.dtype)
        i32 = jnp.int32
        pre = jax.make_jaxpr(self._prefill_raw)(
            jax.ShapeDtypeStruct((1, s0), i32), pages, pages,
            jax.ShapeDtypeStruct((s0 // self.block_size,), i32),
            jax.ShapeDtypeStruct((), i32))
        dec = jax.make_jaxpr(self._decode_raw)(
            jax.ShapeDtypeStruct((b0,), i32), pages, pages,
            jax.ShapeDtypeStruct((b0, self.max_blocks_per_seq), i32),
            jax.ShapeDtypeStruct((b0,), i32))
        return {"prefill": (pre, (1, 2)), "decode": (dec, (1, 2))}

    def compile_decode(self):
        """AOT lower+compile the decode executable at its smallest
        bucket — the compiled-HLO verifier's serving input
        (``analysis/hlo_check``). Returns ``(compiled,
        donated_leaves)``: the page pool's two donated buffers must
        realize input/output aliases (X002 — an unaliased pool doubles
        the engine's HBM footprint), and a single-partition decode
        module must compile with zero collectives (X001)."""
        b0 = self.decode_buckets.sizes[0]
        c = self.cache
        pages = jax.ShapeDtypeStruct(c.k.shape, c.k.dtype)
        i32 = jnp.int32
        compiled = self._decode_fn.lower(
            jax.ShapeDtypeStruct((b0,), i32), pages, pages,
            jax.ShapeDtypeStruct((b0, self.max_blocks_per_seq), i32),
            jax.ShapeDtypeStruct((b0,), i32)).compile()
        return compiled, 2

    def _maybe_lint(self) -> None:
        """FLAGS_static_analysis hook: on first dispatch, lint both step
        graphs, verify the declared plan (one trace feeds both), and —
        final stage — verify the compiled decode module's optimized HLO
        against the plan (X-rules, analysis/hlo_check.py)."""
        if self._linted:
            return
        self._linted = True
        from ..analysis import hlo_check, jaxpr_lint, plan_check
        if jaxpr_lint.analysis_mode() == "off":
            return
        diags = []
        traced = self.trace_steps()
        for name, (closed, donate) in traced.items():
            diags += jaxpr_lint.lint_jaxpr(closed, donate_argnums=donate,
                                           where=f"serving.{name}")
        diags += plan_check.check_plan(self.plan, traced["decode"][0],
                                       donate_argnums=traced["decode"][1],
                                       where="serving")
        try:
            compiled, donated = self.compile_decode()
        except Exception:
            compiled = None  # first dispatch will surface the error
        if compiled is not None:
            diags += hlo_check.check_hlo(self.plan, compiled,
                                         donated_leaves=donated,
                                         where="serving.decode.hlo")
        if diags:
            jaxpr_lint.emit(diags, where="serving")

    # ------------------------------------------------------------------
    # Request lifecycle
    # ------------------------------------------------------------------

    def submit(self, request: Request) -> Union[Sequence, Rejected]:
        """Admit one request, or answer with a typed :class:`Rejected`
        (429-style) when the bounded queue or the host-spill budget is
        over capacity. Malformed requests (a total that can never fit
        ``max_seq_len``) still raise — that is a client contract error,
        not transient overload."""
        total = request.prompt_ids.size + request.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request {request.rid!r}: prompt {request.prompt_ids.size} "
                f"+ max_new_tokens {request.max_new_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        # the prompt must fit a registered prefill bucket on its own
        self.prefill_buckets.fit(request.prompt_ids.size)
        metrics.counter("serving.requests", "requests submitted").inc()
        if not self.sched.can_accept():
            return self._reject(
                request, "queue_full",
                f"waiting queue at max_waiting={self.sched.max_waiting}")
        if (self.max_spilled_bytes is not None
                and self._spilled_bytes > self.max_spilled_bytes):
            return self._reject(
                request, "spill_budget",
                f"host spill {self._spilled_bytes}B over budget "
                f"{self.max_spilled_bytes}B")
        seq = Sequence(request)
        seq.t_submit = time.perf_counter()
        self._seqs[request.rid] = seq
        if self.journal is not None:
            self.journal.submitted(request)
        self.sched.submit(seq)
        self._gauges()
        return seq

    def _reject(self, request: Request, reason: str,
                detail: str) -> Rejected:
        rej = Rejected(request.rid, reason, detail)
        self.rejections.append(rej)
        metrics.counter("serving.rejected",
                        "submissions refused by bounded admission").inc()
        if self.journal is not None:
            self.journal.terminal(request.rid, "rejected", reason)
        request_timeline.current().record(
            rid=request.rid, prompt_tokens=request.prompt_ids.size,
            new_tokens=0, phases_ms={}, total_ms=0.0,
            outcome="rejected", error=f"{reason}: {detail}",
            deadline_ms=(None if request.deadline_s is None
                         else request.deadline_s * 1e3))
        return rej

    def _gauges(self) -> None:
        metrics.gauge("serving.queue_depth",
                      "requests waiting for admission").set(
                          len(self.sched.waiting))
        metrics.gauge("serving.running",
                      "sequences resident in the decode batch").set(
                          len(self.sched.running))

    # -- terminal non-success paths (isolation, deadlines, shedding) ---------

    def _cancel(self, seq: Sequence, status: Status, reason: str,
                *, diagnose: bool = False) -> None:
        """The one exit for every non-FINISHED ending: scheduler
        retirement, provable reclamation of device blocks AND host-spill
        buffers, journal acknowledgment, timeline record, counters. The
        allocator-invariant tests pin the zero-leak property."""
        self.sched.retire(seq, status)
        if seq.block_ids:
            self.cache.allocator.free(seq.block_ids)
            seq.block_ids = []
        if seq.host_kv is not None:
            seq.host_kv = None
            self._account_spill(-seq.spilled_bytes)
            seq.spilled_bytes = 0
        seq.error = reason
        outcome = status.value
        metrics.counter(f"serving.{outcome}",
                        f"requests ending {outcome}").inc()
        if diagnose:
            self._diagnose_failure(seq, reason)
        if self.journal is not None:
            self.journal.terminal(seq.rid, outcome, reason)
        req = seq.request
        end = time.perf_counter()
        request_timeline.current().record(
            rid=seq.rid, prompt_tokens=seq.prompt_len,
            new_tokens=seq.n_generated,
            phases_ms={k: v * 1e3 for k, v in seq.phase_s.items()},
            total_ms=(end - seq.t_submit) * 1e3,
            ttft_ms=((seq.t_first_token - seq.t_submit) * 1e3
                     if seq.t_first_token is not None else None),
            preemptions=seq.preemptions, outcome=outcome, error=reason,
            deadline_ms=(None if req.deadline_s is None
                         else req.deadline_s * 1e3))
        self._gauges()

    def _diagnose_failure(self, seq: Sequence, reason: str) -> None:
        from ..analysis.jaxpr_lint import Diagnostic, emit
        d = Diagnostic(
            rule="F003", name="serving-request-failed", severity="warning",
            message=f"request {seq.rid!r} failed after "
                    f"{seq.n_generated} token(s): {reason}",
            hint="the failure is isolated to this request; the engine "
                 "loop continues and its blocks were reclaimed",
            where="serving.engine")
        self.diagnostics.append(d)
        # Operational finding — forced warn so it is visible even with
        # FLAGS_static_analysis=off (same contract as F001).
        emit([d], where="serving.engine", mode="warn")

    def _account_spill(self, delta_bytes: int) -> None:
        self._spilled_bytes = max(0, self._spilled_bytes + delta_bytes)
        metrics.gauge("serving.spilled_bytes",
                      "bytes of preempted KV held in the host tier").set(
                          self._spilled_bytes)

    def _expire_deadlines(self) -> None:
        """Cancel every live sequence past its deadline — iteration
        granularity, measured from TRUE submission time (``t_submit`` is
        never rewritten by preemption)."""
        now = time.perf_counter()
        live = list(self.sched.waiting) + list(self.sched.running)
        for seq in live:
            d = seq.request.deadline_s
            if d is not None and now - seq.t_submit > d:
                self._cancel(seq, Status.EXPIRED,
                             f"deadline {d * 1e3:.0f}ms exceeded "
                             f"({(now - seq.t_submit) * 1e3:.0f}ms elapsed)")

    def _apply_shed_policy(self) -> None:
        """One policy consult per iteration: set ``mode``, shed at most
        one request (lowest-priority/youngest, waiting first), and in
        degraded mode compute the shrunken decode-bucket cap."""
        pol = self.shed_policy
        if pol is None:
            return
        usable = self.cache.num_blocks - 1
        free_frac = self.cache.allocator.n_free / usable if usable else 0.0
        p99 = percentile(list(self._decode_ms), 99)
        why = pol.overloaded(free_frac, p99)
        if why is None:
            self.mode = "healthy"
            self._degraded_width = None
            return
        self.mode = "degraded" if pol.degrade else "shedding"
        metrics.counter("serving.overload_iterations",
                        "iterations spent in shed/degraded mode").inc()
        # degrade mode preserves residents (they get a smaller bucket);
        # pure shed mode may drop running work to free blocks
        victim = self.sched.shed_candidate(waiting_only=pol.degrade)
        if victim is not None:
            self._cancel(victim, Status.SHED, f"load shed: {why}")
        if pol.degrade and len(self.sched.running) > 1:
            fit = self.decode_buckets.fit(len(self.sched.running))
            smaller = [b for b in self.decode_buckets.sizes if b < fit]
            self._degraded_width = smaller[-1] if smaller else 1

    def _enforce_degraded_width(self) -> None:
        """Degraded mode shrinks the active decode bucket: preempt the
        youngest/lowest-priority residents (the normal LIFO spill path)
        until the batch fits the smaller bucket."""
        cap = self._degraded_width
        if cap is None:
            return
        while len(self.sched.running) > cap:
            victim = self.sched.preempt_victim()
            if victim is None:
                break
            try:
                self._preempt(victim)
            except SpillError as e:
                self._cancel(victim, Status.FAILED,
                             f"KV spill failed: {e}", diagnose=True)

    # -- admission (prefill / restore) --------------------------------------

    def _try_admit(self) -> bool:
        if self.mode != "healthy":
            return False            # overload: pause fresh admissions
        seq = self.sched.peek_waiting()
        if seq is None or not self.sched.has_capacity():
            return False
        if seq.status is Status.PREEMPTED:
            n_need = int(seq.host_kv[0].shape[1])
        else:
            n_need = _ceil_div(seq.prompt_len, self.block_size)
        ids = self.cache.allocator.alloc(n_need)
        if ids is None:
            if not self.sched.running and self.cache.allocator.n_used == 0:
                # an idle pool that still cannot grant the front request
                # will never be able to: fail it (isolation), keep going
                self._cancel(
                    seq, Status.FAILED,
                    f"needs {n_need} KV block(s), pool has only "
                    f"{self.cache.allocator.n_free}", diagnose=True)
                return True
            return False
        self.sched.admit(seq)
        try:
            if seq.status is Status.RUNNING and seq.host_kv is not None:
                self._restore(seq, ids)
            else:
                self._prefill(seq, ids)
        except Exception as e:  # per-sequence device error: isolate it
            if seq.block_ids:
                # blocks granted this admission that _cancel would miss
                extra = [i for i in ids if i not in seq.block_ids]
            else:
                seq.block_ids = list(ids)
                extra = []
            if extra:
                self.cache.allocator.free(extra)
            self._cancel(seq, Status.FAILED,
                         f"{type(e).__name__}: {e}", diagnose=True)
        return True

    def _prefill(self, seq: Sequence, block_ids: List[int]) -> None:
        now = time.perf_counter()
        seq.add_phase("queue", now - seq.t_enqueue)
        bucket = self.prefill_buckets.fit(seq.prompt_len)
        nb_bucket = bucket // self.block_size
        ids = pad_axis(seq.request.prompt_ids[None, :], 1, bucket)
        btab = np.full((nb_bucket,), NULL_BLOCK, np.int32)
        btab[:len(block_ids)] = block_ids
        args = (jnp.asarray(ids, jnp.int32), self.cache.k, self.cache.v,
                jnp.asarray(btab), jnp.asarray(seq.prompt_len, jnp.int32))
        self._maybe_lint()
        self._sent_prefill.observe_tree(
            "serving.prefill", (args[0], args[3], args[4]),
            donate=(1, 2), where="serving.prefill")
        tok, k2, v2 = self._prefill_fn(*args)
        tok = int(tok)  # host sync: honest prefill timing
        self.cache.swap(k2, v2)
        seq.block_ids = list(block_ids)
        seq.block_log.extend(block_ids)
        seq.ctx_len = seq.prompt_len
        seq.out_tokens.append(tok)
        seq.t_first_token = time.perf_counter()
        dur = seq.t_first_token - now
        seq.add_phase("prefill", dur)
        metrics.histogram("serving.prefill_ms",
                          "prefill step wall time (ms)").observe(dur * 1e3)
        if seq.is_finished_by(tok):
            self._finish(seq)

    def _restore(self, seq: Sequence, ids: List[int]) -> None:
        now = time.perf_counter()
        seq.add_phase("queue", now - seq.t_enqueue)
        self.cache.restore(seq.host_kv, ids)
        seq.host_kv = None
        self._account_spill(-seq.spilled_bytes)
        seq.spilled_bytes = 0
        seq.block_ids = list(ids)
        seq.block_log.append(-1)  # spill/restore boundary
        seq.block_log.extend(ids)
        # KV re-materialization substitutes for prefill on resume
        seq.add_phase("prefill", time.perf_counter() - now)

    def _preempt(self, seq: Sequence) -> None:
        self.sched.preempt(seq)
        n_blocks = len(seq.block_ids)
        seq.host_kv = self.cache.spill(seq.block_ids)
        seq.block_ids = []
        seq.spilled_bytes = n_blocks * self.cache.bytes_per_block
        self._account_spill(seq.spilled_bytes)
        # queue time for the preempted span restarts now; t_submit stays
        # the TRUE arrival so latency + deadlines measure end to end
        seq.t_requeue = time.perf_counter()
        metrics.counter("serving.preemptions",
                        "sequences preempted for KV capacity").inc()

    # -- the decode iteration ------------------------------------------------

    def _ensure_decode_blocks(self) -> None:
        """Every running sequence needs a real block for position
        ctx_len before the next iteration; preempt (lowest-priority,
        youngest first) to make room. Pool exhaustion with nothing left
        to preempt fails *that* sequence (F003) — :class:`OutOfBlocksError`
        never crosses the engine loop."""
        for seq in list(self.sched.running):
            if seq.status is not Status.RUNNING:
                continue
            needed = seq.ctx_len // self.block_size + 1
            while len(seq.block_ids) < needed:
                got = self.cache.allocator.alloc(1)
                if got is not None:
                    seq.block_ids.extend(got)
                    seq.block_log.extend(got)
                    continue
                victim = self.sched.preempt_victim(exclude=seq)
                if victim is None:
                    err = OutOfBlocksError(
                        f"sequence {seq.rid!r} needs block "
                        f"{len(seq.block_ids) + 1} of {needed} and there "
                        "is nothing left to preempt — the request "
                        "outgrew the pool")
                    self._cancel(seq, Status.FAILED, str(err),
                                 diagnose=True)
                    break
                try:
                    self._preempt(victim)
                except SpillError as e:
                    self._cancel(victim, Status.FAILED,
                                 f"KV spill failed: {e}", diagnose=True)

    def _decode_iteration(self) -> List[Sequence]:
        batch = self.sched.iteration_batch()
        if not batch:
            return []
        t0 = time.perf_counter()
        width = self.decode_buckets.fit(len(batch))
        m_blocks = self.max_blocks_per_seq
        tokens = np.zeros((width,), np.int32)
        tables = np.full((width, m_blocks), NULL_BLOCK, np.int32)
        lens = np.zeros((width,), np.int32)
        for i, seq in enumerate(batch):
            tokens[i] = seq.out_tokens[-1]
            tables[i, :len(seq.block_ids)] = seq.block_ids
            lens[i] = seq.ctx_len
        args = (jnp.asarray(tokens), self.cache.k, self.cache.v,
                jnp.asarray(tables), jnp.asarray(lens))
        self._maybe_lint()
        self._sent_decode.observe_tree(
            "serving.decode", (args[0], args[3], args[4]),
            donate=(1, 2), where="serving.decode")
        out, k2, v2 = self._decode_fn(*args)
        out = np.asarray(out)  # host sync per iteration (token commit)
        self.cache.swap(k2, v2)
        # Drill seam: a kill here lands AFTER the iteration's compute but
        # BEFORE any token is committed/acknowledged — the relaunch must
        # replay every in-flight request from scratch, exactly once.
        _fault_fire("serve.mid_decode")
        dur = time.perf_counter() - t0
        self._decode_ms.append(dur * 1e3)
        metrics.histogram("serving.decode_step_ms",
                          "decode iteration wall time (ms)").observe(
                              dur * 1e3)
        finished: List[Sequence] = []
        for i, seq in enumerate(batch):
            seq.add_phase("decode", dur)
            seq.ctx_len += 1
            tok = int(out[i])
            seq.out_tokens.append(tok)
            if seq.is_finished_by(tok):
                finished.append(seq)
        for seq in finished:
            self._finish(seq)
        return finished

    def _finish(self, seq: Sequence) -> None:
        t0 = time.perf_counter()
        self.sched.finish(seq)
        if seq.block_ids:
            self.cache.allocator.free(seq.block_ids)
            seq.block_ids = []
        out = seq.full_output()
        seq.output = out
        # Acknowledge BEFORE detokenize/record: once the journal holds the
        # done record (fsynced), a relaunch will not replay this request.
        if self.journal is not None:
            self.journal.done(seq.rid, seq.out_tokens)
        if self.detokenizer is not None:
            seq.text = self.detokenizer(out)
        end = time.perf_counter()
        seq.add_phase("detokenize", end - t0)
        total_ms = (end - seq.t_submit) * 1e3
        ttft_ms = ((seq.t_first_token - seq.t_submit) * 1e3
                   if seq.t_first_token is not None else None)
        request_timeline.current().record(
            rid=seq.rid, prompt_tokens=seq.prompt_len,
            new_tokens=seq.n_generated,
            phases_ms={k: v * 1e3 for k, v in seq.phase_s.items()},
            total_ms=total_ms, ttft_ms=ttft_ms,
            preemptions=seq.preemptions, outcome="ok",
            deadline_ms=(None if seq.request.deadline_s is None
                         else seq.request.deadline_s * 1e3))

    # ------------------------------------------------------------------
    # Driving loop
    # ------------------------------------------------------------------

    def step(self) -> List[Sequence]:
        """One scheduler iteration: expire deadlines, consult the shed
        policy, admit whatever fits (prefill / restore at token
        granularity), top up decode blocks (preempting under pressure),
        run one decode iteration. Returns every sequence that reached a
        terminal state this iteration — FINISHED, and also EXPIRED /
        SHED / FAILED retirements."""
        n0 = len(self.sched.finished)
        self._expire_deadlines()
        self._apply_shed_policy()
        self._enforce_degraded_width()
        while self._try_admit():
            pass
        self._ensure_decode_blocks()
        self._decode_iteration()
        self._gauges()
        return self.sched.finished[n0:]

    def serve(self, requests: Seq[Request],
              respect_arrivals: bool = False
              ) -> Dict[str, Union[Sequence, Rejected]]:
        """Drive the full trace to completion; returns rid -> Sequence
        (with ``.output`` / ``.text`` — check ``.status`` for the
        EXPIRED/SHED/FAILED endings) or the :class:`Rejected` answer for
        requests bounded admission refused. ``respect_arrivals`` replays
        each request's ``arrival_s`` offset instead of submitting
        everything up front."""
        order = sorted(requests, key=lambda r: r.arrival_s) \
            if respect_arrivals else list(requests)
        t0 = time.perf_counter()
        idx = 0
        done: Dict[str, Union[Sequence, Rejected]] = {}
        while idx < len(order) or self.sched.n_pending:
            now = time.perf_counter() - t0
            while idx < len(order) and (
                    not respect_arrivals or order[idx].arrival_s <= now):
                res = self.submit(order[idx])
                if isinstance(res, Rejected):
                    done[res.rid] = res
                idx += 1
            if not self.sched.n_pending:
                if idx < len(order) and respect_arrivals:
                    time.sleep(
                        max(0.0, order[idx].arrival_s -
                            (time.perf_counter() - t0)))
                continue
            for seq in self.step():
                done[seq.rid] = seq
        self.sched.assert_idle()
        return done

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    def compile_report(self) -> Dict[str, Any]:
        """Distinct executable signatures dispatched vs the bucket
        budget — the '≤ n_buckets compilations, O001 silent' check."""
        n_pre = len(self._sent_prefill._seen.get("serving.prefill", ()))
        n_dec = len(self._sent_decode._seen.get("serving.decode", ()))
        return {
            "prefill_signatures": n_pre,
            "decode_signatures": n_dec,
            "budget": len(self.prefill_buckets) + len(self.decode_buckets),
            "prefill_buckets": self.prefill_buckets.sizes,
            "decode_buckets": self.decode_buckets.sizes,
            "within_budget": (n_pre <= len(self.prefill_buckets) and
                              n_dec <= len(self.decode_buckets)),
            "o001_fired": bool(self._sent_prefill.diagnostics or
                               self._sent_decode.diagnostics),
        }
