"""The serving fault drill: serve → kill → relaunch → replay → verify.

The training drill (``fault/drill.py``) proves checkpointed training
recovers bitwise; this is the serving counterpart for ISSUE 9 — the
worker (``serving/_drill_worker.py``) serves a deterministic request
trace under the elastic launcher while a :class:`FaultPlan` SIGKILLs it
**mid-decode** (after an iteration's compute, before any token commit)
and **mid-spill** (inside the paged cache's host spill, before the
blocks are freed). Every incarnation replays exactly the
submitted-but-unacknowledged requests out of the fsynced
:class:`~paddle_tpu.serving.resilience.RequestJournal`, and the drill
asserts the serving resilience contract:

- **zero lost requests** — every trace rid acknowledged;
- **zero duplicated requests** — exactly one acknowledgment each;
- **token-exact survivors** — every served output equals
  ``model.generate`` on the same prompt (greedy), kills or not.

CLI: ``tools/serve_drill.py`` (``--quick`` is the tier-1-safe mode
``tests/test_serve_drill.py`` runs as a subprocess); ``bench.py``
(``BENCH_SERVE``) embeds the recovery stats next to the SLO metrics.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

from .resilience import RequestJournal

__all__ = ["quick_serve_config", "run_serve_drill", "run_overload_drill",
           "report_summary"]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_drill_worker.py")


def quick_serve_config() -> Dict[str, Any]:
    """The tier-1-safe drill: tiny GPT, a trace that forces preemption
    pressure (so the mid-spill seam is reached), two kills — one
    mid-decode, one mid-spill — well under two minutes on a laptop CPU.

    ``prefix_cache=1`` arms the radix tree in the worker and
    ``shared_prefix=N`` gives every trace prompt an N-token common
    prefix, so the relaunch-replay path exercises tree re-attachment
    (ISSUE 13 satellite: token-exactness must survive kills with the
    prefix cache on)."""
    return dict(
        requests=6, prompt_lo=8, prompt_hi=14, max_new=8, trace_seed=3,
        model_seed=7, vocab=128, hidden=48, layers=2, heads=4, max_pos=32,
        block_size=4, num_blocks=10, max_batch=4,
        prefix_cache=0, shared_prefix=0,
        # (kind, counter): decode iteration 4 and the very first spill —
        # both guaranteed to be reached before anything completes
        events=(("mid_decode", 4), ("mid_spill", 1)))


def _write_trace(path: str, cfg: Dict[str, Any]) -> list:
    import numpy as np
    rng = np.random.default_rng(cfg["trace_seed"])
    shared = rng.integers(0, cfg["vocab"],
                          int(cfg.get("shared_prefix", 0))).tolist()
    trace = []
    for i in range(cfg["requests"]):
        plen = int(rng.integers(cfg["prompt_lo"], cfg["prompt_hi"] + 1))
        prompt = shared + rng.integers(0, cfg["vocab"], plen).tolist()
        trace.append({"rid": f"r{i}", "prompt": prompt,
                      "max_new_tokens": int(cfg["max_new"])})
    with open(path, "w") as f:
        for rec in trace:
            f.write(json.dumps(rec) + "\n")
    return trace


def _reference_outputs(trace, cfg) -> Dict[str, list]:
    """Greedy ``model.generate`` on the drill model — the token-exact
    anchor every survivor is compared against."""
    import jax.numpy as jnp
    import numpy as np
    from ._drill_worker import build_model
    model = build_model(cfg)
    refs = {}
    for rec in trace:
        ids = jnp.asarray(np.asarray(rec["prompt"], np.int32)[None])
        refs[rec["rid"]] = np.asarray(model.generate(
            ids, max_new_tokens=rec["max_new_tokens"]))[0].tolist()
    return refs


def run_serve_drill(workdir: str, **overrides: Any) -> Dict[str, Any]:
    """Run the fault-injected serving drill and verify exactly-once +
    token-exactness. Returns the full report; ``ok`` is the verdict."""
    from ..distributed.launch import LaunchConfig, launch
    from ..fault.injection import FaultEvent, FaultPlan

    cfg = quick_serve_config()
    cfg.update(overrides)
    os.makedirs(workdir, exist_ok=True)
    trace = _write_trace(os.path.join(workdir, "trace.jsonl"), cfg)
    plan = FaultPlan([FaultEvent(k, int(s)) for k, s in cfg["events"]])

    env = dict(os.environ)
    env.update({
        "FLAGS_flight_recorder": "on",  # arm the worker's black box
        "FLAGS_fleet_telemetry": "on",  # arm the live telemetry plane
        "FLAGS_fleet_export_interval": "0.2",
        "SERVE_WORK_DIR": workdir,
        "SERVE_PLAN": plan.to_json(),
        "SERVE_CFG": json.dumps({k: v for k, v in cfg.items()
                                 if k != "events"}),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    launch_cfg = LaunchConfig(nproc_per_node=1,
                              log_dir=os.path.join(workdir, "logs"),
                              envs=env)
    t0 = time.perf_counter()
    rc = launch(launch_cfg, WORKER, max_restarts=len(plan) + 2,
                elastic_dir=os.path.join(workdir, "hb"))
    wall_s = time.perf_counter() - t0

    report: Dict[str, Any] = {
        "rc": rc, "wall_s": round(wall_s, 4),
        "plan": json.loads(plan.to_json()),
        "config": {k: (list(v) if isinstance(v, tuple) else v)
                   for k, v in cfg.items()},
    }
    fired = []
    try:
        with open(os.path.join(workdir, "fired.json")) as f:
            fired = sorted(json.load(f))
    except (OSError, ValueError):
        pass
    report["fired_events"] = fired
    if rc != 0:
        report["error"] = f"serve drill worker pod exited rc={rc}"
        report["ok"] = False
        return report

    journal = RequestJournal(os.path.join(workdir, "journal.jsonl"))
    expected = [rec["rid"] for rec in trace]
    once = journal.exactly_once_report(expected)
    report["exactly_once"] = once
    report["restarts"] = max(0, once["launches"] - 1)

    # token-exactness: journal outputs (prompt + generated) vs generate
    refs = _reference_outputs(trace, cfg)
    outs = journal.done_outputs()
    prompts = {rec["rid"]: rec["prompt"] for rec in trace}
    mismatched = [rid for rid, toks in outs.items()
                  if prompts[rid] + toks != refs[rid]]
    report["served"] = len(outs)
    report["token_exact"] = not mismatched
    report["mismatched_rids"] = mismatched

    # postmortem reconstruction from the worker's black boxes + the
    # journals: fired kinds/counters must match the plan and every
    # recorder-served output must carry a journaled ack
    from ..observability import fleet
    report["postmortem"] = fleet.postmortem_report(
        workdir, plan=report["plan"]["events"], expected_rids=expected)

    # live fleet plane cross-check: the drill worker exported snapshots
    # under workdir/fleet the whole time (FLAGS_fleet_telemetry=on) —
    # the final incarnation must have said a closed farewell ("exited"),
    # every killed incarnation must have gone silent without one, and
    # the live goodput ratio must agree with the journal reconstruction
    report["fleet"] = _fleet_section(workdir, journal)
    report["ok"] = bool(
        once["exactly_once"] and not mismatched
        and len(fired) == len(plan)
        and report["restarts"] == len(plan)
        and report["postmortem"]["ok"]
        and report["fleet"]["ok"])
    return report


def _fleet_section(workdir: str, journal: RequestJournal) -> Dict[str, Any]:
    """Drill-end live-plane verdict from the exported snapshots."""
    from ..observability import alerts as fleet_alerts
    from ..observability import live as fleet_live
    view = fleet_live.aggregate(workdir)
    engine = fleet_alerts.AlertEngine(fleet_alerts.default_rules(),
                                      emit_mode="off")
    fired_alerts = engine.evaluate(view)
    worker = view["workers"].get("server.r0", {})
    silent = list(worker.get("silent_incarnations", []))
    if worker and worker.get("status") == "dead":
        silent.append(int(worker.get("incarnation", 0)))
    # live goodput = ok acks / all acks over every incarnation's
    # exported counters; the journal's ack mix is the exact postmortem
    # number it must match (a SIGKILL between an ack and the next
    # export may lag the live *counts*, never the final incarnation's,
    # and the quick drill's remainder all lands there)
    live_gp = view["derived"].get("live_goodput")
    outcomes = journal.ack_outcomes()
    pm_gp = (sum(1 for o in outcomes.values() if o == "done")
             / len(outcomes)) if outcomes else None
    match = (live_gp is not None and pm_gp is not None
             and abs(live_gp - pm_gp) < 1e-9)
    return {
        "workers": {k: w["status"] for k, w in view["workers"].items()},
        "incarnations_seen": int(worker.get("incarnations", 0)),
        "silent_incarnations": silent,
        "final_status": worker.get("status"),
        "live_goodput": live_gp,
        "postmortem_goodput": pm_gp,
        "goodput_match": match,
        "derived": view["derived"],
        "alerts": [a.to_json() for a in fired_alerts],
        "ok": bool(worker) and worker.get("status") == "exited"
        and match,
    }


def run_overload_drill(workdir: str, **overrides: Any) -> Dict[str, Any]:
    """The injected-overload drill: an in-process tiny engine under a
    :class:`~paddle_tpu.serving.resilience.ShedPolicy` is offered more
    work than the paged pool tolerates while the live exporter publishes
    snapshots — the aggregated fleet view must show the sheds and the
    default shed-rate SLO rule (L002) must fire from the exported
    history alone.

    Unlike :func:`run_serve_drill` this never forks: the exporter is
    armed in this process (thread off; explicit ``export_now`` before
    and after ``serve`` brackets the overload window), so the alert
    evaluates a *rate* — registry counters are process-lifetime
    cumulative and other engines may have shed before us, but the
    window delta is exactly this drill's. Returns the report;
    ``ok`` requires sheds > 0, the L002 firing, and the live window
    goodput matching the engine's own outcome mix."""
    import numpy as np

    from ..core.flags import get_flags, set_flags
    from ..observability import alerts as fleet_alerts
    from ..observability import live as fleet_live
    from ._drill_worker import build_model
    from .engine import ServingEngine
    from .resilience import Rejected, ShedPolicy
    from .scheduler import Request, Status

    cfg = quick_serve_config()
    cfg.update(requests=10, events=(), shed_free_frac=0.5)
    cfg.update(overrides)
    os.makedirs(workdir, exist_ok=True)
    trace = _write_trace(os.path.join(workdir, "trace.jsonl"), cfg)

    prev = get_flags(["fleet_telemetry", "fleet_export_interval"])
    set_flags({"fleet_telemetry": "on", "fleet_export_interval": 0.05})
    try:
        exporter = fleet_live.arm(workdir, role="server",
                                  start_thread=False)
        model = build_model(cfg)
        engine = ServingEngine(
            model, block_size=cfg["block_size"],
            num_blocks=cfg["num_blocks"], max_batch=cfg["max_batch"],
            max_seq_len=cfg["max_pos"],
            shed_policy=ShedPolicy(
                min_free_block_frac=float(cfg["shed_free_frac"])))
        requests = [Request(rid=rec["rid"],
                            prompt_ids=np.asarray(rec["prompt"], np.int32),
                            max_new_tokens=int(rec["max_new_tokens"]))
                    for rec in trace]
        exporter.export_now()           # baseline sample: counters before
        done = engine.serve(requests)
        exporter.export_now()           # post sample: the overload delta
        fleet_live.disarm(final_export=True)
    finally:
        fleet_live.disarm(final_export=False)  # no-op on the clean path
        set_flags(prev)

    # engine truth for the window: the drill's own outcome mix
    outcomes = {"ok": 0, "shed": 0, "rejected": 0, "expired": 0,
                "failed": 0}
    for res in done.values():
        if isinstance(res, Rejected):
            outcomes["rejected"] += 1
        elif res.status is Status.FINISHED:
            outcomes["ok"] += 1
        else:
            outcomes[res.status.value] += 1

    view = fleet_live.aggregate(workdir)
    alert_engine = fleet_alerts.AlertEngine(
        fleet_alerts.default_rules(
            min_free_block_frac=float(cfg["shed_free_frac"])),
        emit_mode="off")
    fired = alert_engine.evaluate(view)
    worker = view["workers"].get("server.r0", {})

    # live window goodput: first vs last exported sample (delta over the
    # overload bracket — immune to whatever this process served before)
    hist = worker.get("history", [])
    deltas: Dict[str, float] = {}
    if len(hist) >= 2:
        for k in outcomes:
            deltas[k] = float(hist[-1].get(k, 0) or 0) \
                - float(hist[0].get(k, 0) or 0)
    acks = sum(deltas.values()) if deltas else 0.0
    live_gp = (deltas.get("ok", 0.0) / acks) if acks else None
    truth_acks = sum(outcomes.values())
    truth_gp = (outcomes["ok"] / truth_acks) if truth_acks else None
    gp_match = (live_gp is not None and truth_gp is not None
                and abs(live_gp - truth_gp) < 1e-9)

    shed_alert = any(a.rule == "shed-rate" for a in fired)
    report = {
        "requests": len(trace),
        "outcomes": outcomes,
        "window_deltas": deltas,
        "live_goodput": live_gp,
        "engine_goodput": truth_gp,
        "goodput_match": gp_match,
        "final_status": worker.get("status"),
        "derived": view["derived"],
        "alerts": [a.to_json() for a in fired],
        "shed_alert_fired": shed_alert,
        "ok": bool(outcomes["shed"] > 0 and shed_alert and gp_match
                   and worker.get("status") == "exited"),
    }
    with open(os.path.join(workdir, "overload_report.json"), "w") as f:
        json.dump(report, f, indent=2, sort_keys=True, default=str)
    return report


def report_summary(report: Dict[str, Any]) -> str:
    once = report.get("exactly_once", {})
    lines = [
        f"serve drill rc={report.get('rc')} ok={report.get('ok')} "
        f"wall={report.get('wall_s')}s",
        f"  plan:  {[e['kind'] + '@' + str(e['step']) for e in report['plan']['events']]}",
        f"  fired: {report.get('fired_events')} "
        f"(restarts={report.get('restarts')})",
        f"  requests: {once.get('expected')} expected, "
        f"{once.get('acknowledged')} acknowledged, "
        f"lost={once.get('lost')}, duplicated={once.get('duplicated')}",
        f"  outputs: {report.get('served')} served, "
        f"token_exact={report.get('token_exact')}",
    ]
    pm = report.get("postmortem")
    if pm:
        lines.append(
            f"  postmortem: ok={pm.get('ok')} "
            f"coherent={pm.get('coherent')} "
            f"recorder_files={pm.get('recorder_files')} "
            f"deaths={[(d['kind'], d['step']) for d in pm.get('deaths', [])]}")
    fl = report.get("fleet")
    if fl:
        lines.append(
            f"  fleet: final={fl.get('final_status')} "
            f"silent_incs={fl.get('silent_incarnations')} "
            f"goodput live={fl.get('live_goodput')} "
            f"pm={fl.get('postmortem_goodput')} "
            f"match={fl.get('goodput_match')} "
            f"alerts={[a['rule'] for a in fl.get('alerts', [])]}")
    return "\n".join(lines)
