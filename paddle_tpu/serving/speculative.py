"""Speculative decoding: drafters + the accepted-length-driven gamma.

Speculative decoding (Leviathan et al. 2023) on the paged substrate: a
cheap **drafter** proposes ``gamma`` tokens per iteration and the target
model verifies the whole proposal in ONE bucketed decode-gamma dispatch
(``engine._make_extend`` — gamma+1 query positions over the gathered
pages, KV written in-program exactly like the decode step). The greedy
accept rule: walk the proposal, keep ``d_j`` while it equals the
target's own argmax after the accepted prefix, then commit the target's
token at the first mismatch — every iteration commits between 1 and
gamma+1 tokens and the committed stream is exactly the target's greedy
decode, drafts or no drafts.

Two drafters:

- :class:`NGramDrafter` (the default): prompt-lookup / self-speculation
  — propose the continuation of the longest committed-history suffix
  match. Pure host work, zero extra device state, composes freely with
  the prefix cache and chunked prefill; strong on the repetitive spans
  (templates, code, greedy loops) where speculation pays at all.
- :class:`ModelDrafter`: a small causal LM over a **mirrored paged
  pool** — same ``num_blocks``/``block_size``/block ids as the target
  pool, drafter-sized pages — so the drafter's KV rides the exact same
  block tables, spills and restores with its sequence, and shares
  prefix pages whenever the target does. The engine builds its
  executables from the same prefill/decode/extend builders as the
  target's.

Accepted-length feedback: the engine records every iteration's accepted
length into the ``serving.spec_accept_len`` histogram and (per target/
drafter key) hands the sample to :func:`tune_gamma`, which persists a
recommended gamma in the kernel autotune cache — ``FLAGS_serve_speculative
= -1`` (or ``spec_gamma=None``) reads it back via :func:`pick_gamma`.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

__all__ = ["NGramDrafter", "ModelDrafter", "pick_gamma", "tune_gamma",
           "store_gamma", "DEFAULT_GAMMA"]

DEFAULT_GAMMA = 4
_TUNE_KERNEL = "serve_spec_gamma"


class NGramDrafter:
    """Prompt-lookup drafter: longest-suffix-match continuation.

    Given the committed token history (prompt + generated), find the
    most recent earlier occurrence of the longest current suffix (down
    to ``min_match`` tokens) and propose the tokens that followed it.
    No device state, no weights — the proposal either matches the
    target's greedy continuation (repetitive spans) and multiple tokens
    commit per dispatch, or it costs one ordinary-sized verify step.
    """

    kind = "ngram"

    def __init__(self, max_match: int = 4, min_match: int = 1,
                 repeat_fallback: bool = True):
        if min_match < 1 or max_match < min_match:
            raise ValueError(f"bad match window [{min_match}, {max_match}]")
        self.max_match = int(max_match)
        self.min_match = int(min_match)
        #: with no suffix match, propose repeating the frontier token —
        #: greedy decodes spend long spans in fixed points/short cycles,
        #: and a wrong free proposal costs nothing (the verify dispatch
        #: runs at gamma width either way)
        self.repeat_fallback = bool(repeat_fallback)

    def propose(self, history: Sequence[int], gamma: int) -> List[int]:
        """Up to ``gamma`` proposed tokens (possibly fewer/empty)."""
        h = list(int(t) for t in history)
        n = len(h)
        for m in range(min(self.max_match, n - 1), self.min_match - 1, -1):
            suffix = h[n - m:]
            # newest earlier occurrence wins (recent context repeats)
            for start in range(n - m - 1, -1, -1):
                if h[start:start + m] == suffix:
                    cont = h[start + m:start + m + gamma]
                    if cont:
                        return cont
        if self.repeat_fallback and h:
            return [h[-1]] * gamma
        return []


class ModelDrafter:
    """A drafter causal LM sharing the target's block geometry.

    Thin policy object: the serving engine owns the mirrored
    :class:`~.paged_cache.PagedKVCache` and the drafter's compiled
    prefill/decode/extend executables (built from the same builders as
    the target's). The drafter model must share the target's vocabulary
    and ``GPTForCausalLM`` surface (``.gpt.wte/wpe/h/ln_f``,
    ``.logits``); it may differ in depth/width/heads — its pages are
    sized from its own config.
    """

    kind = "model"

    def __init__(self, model):
        model.eval()
        self.model = model


def _cache_key(target_desc: str, drafter_desc: str) -> str:
    return f"{target_desc}|{drafter_desc}"


def pick_gamma(target_desc: str, drafter_desc: str,
               default: int = DEFAULT_GAMMA) -> int:
    """The persisted accepted-length-derived gamma for this target/
    drafter pair, or ``default`` when never tuned."""
    from ..ops._pallas.autotune import get_cache
    hit = get_cache().get(_TUNE_KERNEL, _cache_key(target_desc,
                                                   drafter_desc))
    if isinstance(hit, (int, float)) and int(hit) >= 1:
        return int(hit)
    return int(default)


def store_gamma(target_desc: str, drafter_desc: str, gamma: int,
                measured_ms: float = 0.0) -> int:
    """Persist a measured-winner gamma directly (the bench's gamma
    sweep stores the throughput-best arm; :func:`tune_gamma` is the
    accepted-length heuristic for when no sweep ran)."""
    from ..ops._pallas.autotune import get_cache
    gamma = int(gamma)
    get_cache().put(_TUNE_KERNEL, _cache_key(target_desc, drafter_desc),
                    gamma, measured_ms=measured_ms)
    return gamma


def tune_gamma(target_desc: str, drafter_desc: str,
               accept_lens: Sequence[int],
               max_gamma: int = 8) -> Optional[int]:
    """Persist the gamma the measured accepted-length distribution
    supports: mean accepted length rounded up, clamped to
    ``[1, max_gamma]`` — proposing far past the mean acceptance buys
    only rejected drafter work. Returns the stored gamma (None when the
    sample is empty)."""
    lens = [int(x) for x in accept_lens]
    if not lens:
        return None
    mean = float(np.mean(lens))
    gamma = int(min(max(1, int(np.ceil(mean))), max_gamma))
    from ..ops._pallas.autotune import get_cache
    get_cache().put(_TUNE_KERNEL,
                    _cache_key(target_desc, drafter_desc), gamma,
                    measured_ms=mean)
    return gamma
