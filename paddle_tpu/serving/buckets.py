"""Bucketed-shape compilation for ragged serving traffic.

XLA compiles one executable per abstract input signature, and real
serving traffic is ragged: every distinct prompt length or batch width
would pay a full compile (the recompile churn the O001 sentinel exists
to catch). The fix is the standard one (vLLM / TPU serving stacks):
register a small, fixed set of shape buckets, pad every dispatch up to
its bucket, and the executable count is capped at ``len(buckets)`` no
matter what the traffic looks like. Padding work is bounded by the
bucket spacing (< 2x for the power-of-two ladder) and the padded tail is
masked out of attention by per-sequence lengths.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BucketSet", "pow2_buckets", "pad_axis"]


def pow2_buckets(lo: int, hi: int) -> Tuple[int, ...]:
    """Power-of-two ladder covering [lo, hi]: the default bucket set
    (≤ log2(hi/lo)+1 executables, ≤ 2x padding waste)."""
    if lo < 1 or hi < lo:
        raise ValueError(f"bad bucket range [{lo}, {hi}]")
    out: List[int] = []
    b = 1
    while b < lo:
        b *= 2
    while b < hi:
        out.append(b)
        b *= 2
    out.append(b)
    return tuple(out)


class BucketSet:
    """A registered, sorted set of sizes with a fit-up policy.

    ``grow=False`` (the serving engine): sizes past the largest bucket
    are a hard error — the compile budget is a promise. ``grow=True``
    (the generic AOT predictor): unseen large sizes extend the ladder by
    powers of two, so the executable count stays logarithmic in the
    largest size ever seen rather than linear in distinct sizes.
    """

    def __init__(self, sizes: Iterable[int], grow: bool = False):
        uniq = sorted({int(s) for s in sizes})
        if not uniq or uniq[0] < 1:
            raise ValueError(f"bucket sizes must be positive: {uniq}")
        self._sizes = uniq
        self.grow = grow

    @property
    def sizes(self) -> List[int]:
        return list(self._sizes)

    def __len__(self) -> int:
        return len(self._sizes)

    def __contains__(self, n: int) -> bool:
        return n in self._sizes

    def fit(self, n: int) -> int:
        """Smallest registered bucket >= n. In ``grow`` mode the set IS
        the power-of-two ladder, materialized rung by rung as sizes are
        seen — fit returns the next power of two >= n (registering it),
        so padding waste stays < 2x and distinct buckets stay
        logarithmic."""
        n = int(n)
        if n < 1:
            raise ValueError(f"size must be positive, got {n}")
        if self.grow:
            b = 1
            while b < n:
                b *= 2
            if b not in self._sizes:
                self._sizes.append(b)
                self._sizes.sort()
            return b
        for s in self._sizes:
            if s >= n:
                return s
        raise ValueError(
            f"size {n} exceeds the largest registered bucket "
            f"{self._sizes[-1]} (buckets: {self._sizes})")

    def __repr__(self) -> str:
        return f"BucketSet({self._sizes}, grow={self.grow})"


def pad_axis(arr: np.ndarray, axis: int, size: int,
             fill=0) -> np.ndarray:
    """Pad one axis of a host array up to ``size`` with ``fill`` (no-op
    when already there)."""
    arr = np.asarray(arr)
    cur = arr.shape[axis]
    if cur == size:
        return arr
    if cur > size:
        raise ValueError(f"axis {axis} is {cur}, larger than bucket {size}")
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, size - cur)
    return np.pad(arr, pad, constant_values=fill)
