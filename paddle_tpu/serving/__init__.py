"""paddle_tpu.serving — the production inference tier.

The one-shot AOT predictor (:mod:`paddle_tpu.inference`) answers one
request at a time with no KV reuse; this package is the engine that
serves *traffic*: a block-paged KV cache in device memory with a
deterministic free-list allocator and host-memory spill for preempted
sequences (vLLM/PagedAttention, SOSP'23), a continuous-batching
scheduler that re-forms the decode batch at token-iteration granularity
(Orca, OSDI'22), and bucketed-shape compilation so ragged traffic
compiles a bounded executable set with the O001 recompile sentinel
standing guard. The resilience tier (:mod:`.resilience`, RESILIENCE.md)
makes the engine degrade instead of dying: per-request deadlines and
priorities, bounded admission with typed :class:`Rejected` backpressure,
overload load shedding (:class:`ShedPolicy`), per-request failure
isolation (F003 — pool exhaustion and spill errors never cross the
engine loop), and the exactly-once :class:`RequestJournal` the serve
drill (``tools/serve_drill.py``) kills the process against.
``bench.py`` (``BENCH_SERVE``) measures tokens/s and p50/p99 request
latency against the sequential one-shot baseline plus SLO attainment
and shed rate from a fault-injected overload trace;
``tools/serve_bench.py`` replays request traces (``--deadline-ms`` /
``--fail-on-slo`` is the CI gate form); ``lint_graph --model serving``
statically verifies the prefill/decode programs and the declared
dispatch plan.
"""

from .buckets import BucketSet, pow2_buckets  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .paged_cache import (BlockAllocator, NULL_BLOCK,  # noqa: F401
                          OutOfBlocksError, PagedKVCache, SpillError)
from .prefix_tree import PrefixCache, PrefixNode  # noqa: F401
from .resilience import (Rejected, RequestJournal,  # noqa: F401
                         ShedPolicy)
from .scheduler import (FCFSScheduler, Request, Sequence,  # noqa: F401
                        Status, TERMINAL_STATUSES)
from .speculative import (ModelDrafter, NGramDrafter,  # noqa: F401
                          pick_gamma, tune_gamma)

__all__ = [
    "ServingEngine", "Request", "Sequence", "Status", "FCFSScheduler",
    "PagedKVCache", "BlockAllocator", "OutOfBlocksError", "SpillError",
    "NULL_BLOCK", "BucketSet", "pow2_buckets",
    "Rejected", "RequestJournal", "ShedPolicy", "TERMINAL_STATUSES",
    "PrefixCache", "PrefixNode", "NGramDrafter", "ModelDrafter",
    "pick_gamma", "tune_gamma",
]
