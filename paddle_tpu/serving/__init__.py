"""paddle_tpu.serving — the production inference tier.

The one-shot AOT predictor (:mod:`paddle_tpu.inference`) answers one
request at a time with no KV reuse; this package is the engine that
serves *traffic*: a block-paged KV cache in device memory with a
deterministic free-list allocator and host-memory spill for preempted
sequences (vLLM/PagedAttention, SOSP'23), a continuous-batching
scheduler that re-forms the decode batch at token-iteration granularity
(Orca, OSDI'22), and bucketed-shape compilation so ragged traffic
compiles a bounded executable set with the O001 recompile sentinel
standing guard. ``bench.py`` (``BENCH_SERVE``) measures tokens/s and
p50/p99 request latency against the sequential one-shot baseline;
``tools/serve_bench.py`` replays request traces; ``lint_graph --model
serving`` statically verifies the prefill/decode programs and the
declared dispatch plan.
"""

from .buckets import BucketSet, pow2_buckets  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .paged_cache import (BlockAllocator, NULL_BLOCK,  # noqa: F401
                          OutOfBlocksError, PagedKVCache)
from .scheduler import FCFSScheduler, Request, Sequence, Status  # noqa: F401

__all__ = [
    "ServingEngine", "Request", "Sequence", "Status", "FCFSScheduler",
    "PagedKVCache", "BlockAllocator", "OutOfBlocksError", "NULL_BLOCK",
    "BucketSet", "pow2_buckets",
]
