"""Continuous-batching scheduler: iteration-level request admission.

Orca's (OSDI'22) observation, applied here: a serving batch must be
re-formed at *token-iteration* granularity, not request granularity —
a static batch runs at the speed of its longest member and admits new
work only at batch boundaries, while iteration-level scheduling admits a
request the moment a decode slot and KV blocks are free, and retires a
sequence the token it finishes. The policy is FCFS with LIFO preemption
(vLLM's default): requests are admitted in arrival order, and when the
block pool runs dry the *youngest* running sequence is preempted (its KV
spilled to host) — the one with the least sunk prefill work and the
shortest spill payload — then resumed, at the front of the queue, when
capacity returns.

Resilience semantics (the overload half of the Orca/vLLM story) live in
the same state machine: the waiting deque can be **bounded**
(``max_waiting`` — the engine answers over-budget submissions with a
typed :class:`~paddle_tpu.serving.resilience.Rejected` instead of
growing the queue forever), every request can carry a **deadline** and a
**priority**, and three more terminal states exist beyond ``FINISHED``:
``EXPIRED`` (deadline passed — cancelled at iteration granularity),
``SHED`` (dropped by the overload policy), and ``FAILED`` (a
per-request device/capacity error isolated to that request). Victim
selection for both preemption and shedding is lowest-priority-first with
the original LIFO (youngest) tie-break, so equal-priority traffic
behaves exactly as before.

This module is pure host-side bookkeeping (queues and state machines);
the engine executes the device work and reports back. Everything is
deterministic under a fixed submission order — no wall-clock policy
inputs — which the block-assignment regression test pins.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from collections import deque

__all__ = ["Request", "Sequence", "Status", "FCFSScheduler",
           "TERMINAL_STATUSES"]


class Status(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"
    EXPIRED = "expired"      # deadline passed; cancelled, blocks reclaimed
    SHED = "shed"            # dropped by the overload policy
    FAILED = "failed"        # per-request error, isolated from the loop


#: Terminal states a sequence can end in (everything but the three live
#: queue states). ``finished`` holds all of them, in retirement order.
TERMINAL_STATUSES = frozenset(
    {Status.FINISHED, Status.EXPIRED, Status.SHED, Status.FAILED})


@dataclass
class Request:
    """One client request: a prompt and a generation budget."""

    rid: str
    prompt_ids: np.ndarray          # [prompt_len] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_s: float = 0.0          # offset into the trace (replay traces)
    deadline_s: Optional[float] = None  # SLO: finish within this of submit
    priority: int = 0               # higher = kept longer under overload

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size < 1:
            raise ValueError(f"request {self.rid!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid!r}: max_new_tokens "
                             f"{self.max_new_tokens}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"request {self.rid!r}: deadline_s "
                             f"{self.deadline_s}")


@dataclass
class Sequence:
    """Runtime state of one request inside the engine."""

    request: Request
    status: Status = Status.WAITING
    ctx_len: int = 0                     # tokens committed to KV
    out_tokens: List[int] = field(default_factory=list)
    block_ids: List[int] = field(default_factory=list)
    host_kv: Any = None                  # spilled KV while PREEMPTED
    spilled_bytes: int = 0               # host bytes held while PREEMPTED
    preemptions: int = 0
    # -- prefix sharing (FLAGS_serve_prefix_cache) ------------------------
    # the first n_shared_blocks of block_ids are copy-on-write tree pages
    # (one allocator ref held per attached sequence); prefix_nodes is the
    # matching trie chain. Both stay empty on the private-KV path.
    n_shared_blocks: int = 0
    prefix_nodes: List[Any] = field(default_factory=list)
    # -- chunked prefill (FLAGS_serve_chunked_prefill) --------------------
    # prompt tokens whose KV is committed; the one-shot path jumps this
    # straight to prompt_len inside _prefill.
    prefill_pos: int = 0
    # -- speculative decoding (FLAGS_serve_speculative) -------------------
    host_draft_kv: Any = None            # drafter-pool mirror of host_kv
    draft_ctx: int = 0                   # tokens with drafter KV written
    error: Optional[str] = None          # reason for a non-FINISHED ending
    # every block id ever assigned, in grant order (spill boundaries as
    # -1): the determinism regression's witness
    block_log: List[int] = field(default_factory=list)
    # phase accounting (engine-stamped, seconds). ``t_submit`` is the TRUE
    # arrival time and is never rewritten; ``t_requeue`` restarts the
    # queue-phase clock on preemption so end-to-end latency (and the
    # deadline check) still measure from submission.
    t_submit: float = 0.0
    t_requeue: Optional[float] = None
    t_first_token: Optional[float] = None
    phase_s: Dict[str, float] = field(default_factory=dict)

    @property
    def rid(self) -> str:
        return self.request.rid

    @property
    def t_enqueue(self) -> float:
        """Start of the current wait span: the last preemption requeue if
        one happened, else the original submission."""
        return self.t_requeue if self.t_requeue is not None else self.t_submit

    @property
    def prompt_len(self) -> int:
        return int(self.request.prompt_ids.size)

    @property
    def n_generated(self) -> int:
        return len(self.out_tokens)

    def add_phase(self, name: str, dur_s: float) -> None:
        self.phase_s[name] = self.phase_s.get(name, 0.0) + dur_s

    def is_finished_by(self, token: int) -> bool:
        eos = self.request.eos_token_id
        return ((eos is not None and token == eos) or
                self.n_generated >= self.request.max_new_tokens)

    def full_output(self) -> np.ndarray:
        return np.concatenate([self.request.prompt_ids,
                               np.asarray(self.out_tokens, np.int32)])


class FCFSScheduler:
    """Arrival-order admission, LIFO preemption, iteration batches.

    ``max_waiting`` bounds the waiting deque: :meth:`can_accept` is the
    admission-control gate the engine consults before :meth:`submit` —
    when full, the engine answers with a typed ``Rejected`` (429-style
    backpressure) instead of queueing unboundedly. ``None`` keeps the
    historical unbounded behavior.
    """

    def __init__(self, max_batch: int, max_waiting: Optional[int] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch {max_batch}")
        if max_waiting is not None and max_waiting < 1:
            raise ValueError(f"max_waiting {max_waiting}")
        self.max_batch = int(max_batch)
        self.max_waiting = None if max_waiting is None else int(max_waiting)
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []   # admission order
        self.finished: List[Sequence] = []

    # -- queue transitions ---------------------------------------------------

    def can_accept(self) -> bool:
        """Room in the bounded waiting queue (preempted residents do not
        count against it — they were already admitted once)."""
        if self.max_waiting is None:
            return True
        fresh = sum(1 for s in self.waiting if s.status is Status.WAITING)
        return fresh < self.max_waiting

    def submit(self, seq: Sequence) -> None:
        seq.status = Status.WAITING
        self.waiting.append(seq)

    def peek_waiting(self) -> Optional[Sequence]:
        return self.waiting[0] if self.waiting else None

    def has_capacity(self) -> bool:
        return len(self.running) < self.max_batch

    def admit(self, seq: Sequence) -> None:
        assert self.waiting and self.waiting[0] is seq, \
            "admission must be FCFS (engine admitted out of order)"
        self.waiting.popleft()
        seq.status = Status.RUNNING
        self.running.append(seq)

    def preempt_victim(self, exclude: Optional[Sequence] = None,
                       cost=None) -> Optional[Sequence]:
        """Lowest-priority running sequence other than ``exclude``,
        youngest (LIFO) within a priority class — with the default
        priority 0 everywhere this is exactly the historical LIFO pick.

        ``cost`` (optional, ``seq -> int``) is the prefix-sharing cost
        model: the number of **private** (refcount-1) blocks a
        preemption would actually free. When given, the pick within a
        priority class is the sequence freeing the MOST private blocks
        (tie-broken by the original LIFO order) — preempting a cheap
        prefix-sharer relieves almost nothing while re-queueing its
        work, so the expensive private-KV hog goes first. ``cost=None``
        (the flag-off path) is bitwise-identical to the historical
        behavior."""
        best: Optional[Sequence] = None
        best_cost = -1
        for seq in reversed(self.running):      # youngest first
            if seq is exclude:
                continue
            if best is None or seq.request.priority < best.request.priority:
                best = seq
                best_cost = cost(seq) if cost is not None else 0
            elif (cost is not None
                  and seq.request.priority == best.request.priority
                  and cost(seq) > best_cost):
                best = seq
                best_cost = cost(seq)
        return best

    def shed_candidate(self, waiting_only: bool = False,
                       cost=None) -> Optional[Sequence]:
        """The cheapest work to drop under overload: lowest priority,
        youngest within the class; waiting work first (no or least sunk
        device work), then — unless ``waiting_only`` (degrade mode keeps
        residents and shrinks their bucket instead) — running. With the
        prefix-sharing ``cost`` model (private blocks held), the pick
        within a priority class prefers the sequence whose drop frees
        the most private blocks — shedding a prefix-sharer frees almost
        nothing. ``cost=None`` keeps the historical order bitwise."""
        pools = [list(self.waiting)]
        if not waiting_only:
            pools.append(self.running)
        for pool in pools:
            if pool:
                if cost is None:
                    # max t_submit = youngest
                    return min(pool, key=lambda s: (s.request.priority,
                                                    -s.t_submit))
                return min(pool, key=lambda s: (s.request.priority,
                                                -cost(s), -s.t_submit))
        return None

    def preempt(self, seq: Sequence) -> None:
        self.running.remove(seq)
        seq.status = Status.PREEMPTED
        seq.preemptions += 1
        # Front of the queue: the preempted sequence has sunk work and,
        # under FCFS, arrived before everything still waiting.
        self.waiting.appendleft(seq)

    def finish(self, seq: Sequence) -> None:
        self.retire(seq, Status.FINISHED)

    def retire(self, seq: Sequence, status: Status) -> None:
        """Move ``seq`` from whichever live queue holds it into a terminal
        state — the one exit used by normal completion, deadline expiry,
        load shedding, and per-request failure isolation alike."""
        if status not in TERMINAL_STATUSES:
            raise ValueError(f"retire to non-terminal status {status}")
        if seq in self.running:
            self.running.remove(seq)
        else:
            try:
                self.waiting.remove(seq)
            except ValueError:
                pass  # already out of both queues (e.g. failed mid-admit)
        seq.status = status
        self.finished.append(seq)

    # -- iteration view ------------------------------------------------------

    def iteration_batch(self) -> List[Sequence]:
        """The sequences decoding this iteration, in admission order."""
        return list(self.running)

    @property
    def n_pending(self) -> int:
        return len(self.waiting) + len(self.running)

    def assert_idle(self) -> None:
        if self.waiting or self.running:
            raise RuntimeError(
                f"scheduler not drained: {len(self.waiting)} waiting, "
                f"{len(self.running)} running")
