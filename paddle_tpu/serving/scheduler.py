"""Continuous-batching scheduler: iteration-level request admission.

Orca's (OSDI'22) observation, applied here: a serving batch must be
re-formed at *token-iteration* granularity, not request granularity —
a static batch runs at the speed of its longest member and admits new
work only at batch boundaries, while iteration-level scheduling admits a
request the moment a decode slot and KV blocks are free, and retires a
sequence the token it finishes. The policy is FCFS with LIFO preemption
(vLLM's default): requests are admitted in arrival order, and when the
block pool runs dry the *youngest* running sequence is preempted (its KV
spilled to host) — the one with the least sunk prefill work and the
shortest spill payload — then resumed, at the front of the queue, when
capacity returns.

This module is pure host-side bookkeeping (queues and state machines);
the engine executes the device work and reports back. Everything is
deterministic under a fixed submission order — no wall-clock policy
inputs — which the block-assignment regression test pins.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from collections import deque

__all__ = ["Request", "Sequence", "Status", "FCFSScheduler"]


class Status(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"


@dataclass
class Request:
    """One client request: a prompt and a generation budget."""

    rid: str
    prompt_ids: np.ndarray          # [prompt_len] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_s: float = 0.0          # offset into the trace (replay traces)

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size < 1:
            raise ValueError(f"request {self.rid!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid!r}: max_new_tokens "
                             f"{self.max_new_tokens}")


@dataclass
class Sequence:
    """Runtime state of one request inside the engine."""

    request: Request
    status: Status = Status.WAITING
    ctx_len: int = 0                     # tokens committed to KV
    out_tokens: List[int] = field(default_factory=list)
    block_ids: List[int] = field(default_factory=list)
    host_kv: Any = None                  # spilled KV while PREEMPTED
    preemptions: int = 0
    # every block id ever assigned, in grant order (spill boundaries as
    # -1): the determinism regression's witness
    block_log: List[int] = field(default_factory=list)
    # phase accounting (engine-stamped, seconds)
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    phase_s: Dict[str, float] = field(default_factory=dict)

    @property
    def rid(self) -> str:
        return self.request.rid

    @property
    def prompt_len(self) -> int:
        return int(self.request.prompt_ids.size)

    @property
    def n_generated(self) -> int:
        return len(self.out_tokens)

    def add_phase(self, name: str, dur_s: float) -> None:
        self.phase_s[name] = self.phase_s.get(name, 0.0) + dur_s

    def is_finished_by(self, token: int) -> bool:
        eos = self.request.eos_token_id
        return ((eos is not None and token == eos) or
                self.n_generated >= self.request.max_new_tokens)

    def full_output(self) -> np.ndarray:
        return np.concatenate([self.request.prompt_ids,
                               np.asarray(self.out_tokens, np.int32)])


class FCFSScheduler:
    """Arrival-order admission, LIFO preemption, iteration batches."""

    def __init__(self, max_batch: int):
        if max_batch < 1:
            raise ValueError(f"max_batch {max_batch}")
        self.max_batch = int(max_batch)
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []   # admission order
        self.finished: List[Sequence] = []

    # -- queue transitions ---------------------------------------------------

    def submit(self, seq: Sequence) -> None:
        seq.status = Status.WAITING
        self.waiting.append(seq)

    def peek_waiting(self) -> Optional[Sequence]:
        return self.waiting[0] if self.waiting else None

    def has_capacity(self) -> bool:
        return len(self.running) < self.max_batch

    def admit(self, seq: Sequence) -> None:
        assert self.waiting and self.waiting[0] is seq, \
            "admission must be FCFS (engine admitted out of order)"
        self.waiting.popleft()
        seq.status = Status.RUNNING
        self.running.append(seq)

    def preempt_victim(self, exclude: Optional[Sequence] = None
                       ) -> Optional[Sequence]:
        """Youngest running sequence other than ``exclude`` (LIFO)."""
        for seq in reversed(self.running):
            if seq is not exclude:
                return seq
        return None

    def preempt(self, seq: Sequence) -> None:
        self.running.remove(seq)
        seq.status = Status.PREEMPTED
        seq.preemptions += 1
        # Front of the queue: the preempted sequence has sunk work and,
        # under FCFS, arrived before everything still waiting.
        self.waiting.appendleft(seq)

    def finish(self, seq: Sequence) -> None:
        self.running.remove(seq)
        seq.status = Status.FINISHED
        self.finished.append(seq)

    # -- iteration view ------------------------------------------------------

    def iteration_batch(self) -> List[Sequence]:
        """The sequences decoding this iteration, in admission order."""
        return list(self.running)

    @property
    def n_pending(self) -> int:
        return len(self.waiting) + len(self.running)

    def assert_idle(self) -> None:
        if self.waiting or self.running:
            raise RuntimeError(
                f"scheduler not drained: {len(self.waiting)} waiting, "
                f"{len(self.running)} running")
