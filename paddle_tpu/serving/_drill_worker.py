"""The serve-drill worker: a serving engine process that survives being
SIGKILLed mid-decode and mid-spill.

Runs as one container under the elastic launcher (``serving/drill.py``
wires it through ``ElasticManager``, exactly like the training drill's
``fault/_trainer.py``). On every incarnation it reads the request trace
and the exactly-once :class:`~paddle_tpu.serving.resilience.RequestJournal`,
replays precisely the submitted-but-unacknowledged requests, and arms the
fault injector's serving fire points:

- ``serve.mid_decode`` — fires after a decode iteration's compute, before
  any token of that iteration is committed (``mid_decode`` kind; the
  injector's "step" is the engine's decode-iteration counter);
- ``serve.mid_spill`` — fires inside ``PagedKVCache.spill`` after the
  host gather, before the device blocks are freed (``mid_spill`` kind;
  counter = spill ordinal).

Env contract (all prefixed SERVE_): ``SERVE_WORK_DIR`` (required; holds
``trace.jsonl``, ``journal.jsonl``, ``fired.json``), ``SERVE_PLAN``
(FaultPlan JSON; empty = no faults), ``SERVE_CFG`` (JSON engine/model
config — see ``drill.quick_serve_config``).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if __name__ == "__main__":  # subprocess mode: the launcher passes a path
    sys.path.insert(0, REPO)


def build_model(cfg):
    import paddle_tpu as paddle
    from paddle_tpu.text.models.gpt import GPTForCausalLM, gpt_tiny
    paddle.seed(int(cfg["model_seed"]))
    model = GPTForCausalLM(gpt_tiny(
        vocab_size=cfg["vocab"], hidden_size=cfg["hidden"],
        num_layers=cfg["layers"], num_heads=cfg["heads"],
        max_position_embeddings=cfg["max_pos"]))
    model.eval()
    return model


def load_trace(path):
    """trace.jsonl -> list of Request (deterministic order)."""
    import numpy as np
    from paddle_tpu.serving import Request
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            out.append(Request(
                rid=rec["rid"],
                prompt_ids=np.asarray(rec["prompt"], np.int32),
                max_new_tokens=int(rec["max_new_tokens"]),
                eos_token_id=rec.get("eos_token_id"),
                deadline_s=rec.get("deadline_s"),
                priority=int(rec.get("priority", 0))))
    return out


def arm_serving_faults(workdir, plan_json):
    """Arm the two serving fire points against the (possibly empty)
    plan. The injector's fired-event journal lives next to the request
    journal so a relaunch never replays a delivered kill."""
    from paddle_tpu.fault.injection import (FaultInjector, FaultPlan,
                                            register_fire_point)
    plan = FaultPlan.from_json(plan_json or "")
    inj = FaultInjector(plan, workdir)
    counters = {"mid_decode": 0, "mid_spill": 0}

    def seam(kind):
        def cb():
            counters[kind] += 1
            inj.poll_event(kind, counters[kind])
        return cb

    register_fire_point("serve.mid_decode", seam("mid_decode"))
    register_fire_point("serve.mid_spill", seam("mid_spill"))
    return inj


def run(workdir, cfg, plan_json=""):
    from paddle_tpu.observability import flight_recorder as flr
    from paddle_tpu.observability import live
    from paddle_tpu.serving import RequestJournal, ServingEngine
    from paddle_tpu.serving.resilience import prompt_hash

    # the serving black box: request outcomes + fired faults survive the
    # SIGKILLs this worker exists to absorb (no-op unless the flag is on)
    flr.arm_if_enabled(os.path.join(workdir, "flr"), role="server")
    # the live plane: periodic registry snapshots under workdir/fleet
    # (shares the recorder's incarnation index when both are armed;
    # no-op unless FLAGS_fleet_telemetry=on)
    live.arm_if_enabled(workdir, role="server")
    trace = load_trace(os.path.join(workdir, "trace.jsonl"))
    journal = RequestJournal(os.path.join(workdir, "journal.jsonl"))
    pending_rids = set(journal.pending_rids([r.rid for r in trace]))
    if not pending_rids:
        return 0  # a previous incarnation acknowledged everything
    # replay integrity: the journaled prompt content hashes must match
    # what the trace hands a relaunched incarnation — a drifted trace
    # would otherwise silently serve different prompts under old rids
    shas = journal.prompt_hashes()
    for r in trace:
        if r.rid in shas and shas[r.rid] != prompt_hash(r.prompt_ids):
            raise RuntimeError(
                f"replay trace prompt for {r.rid!r} does not match the "
                f"journaled submission hash {shas[r.rid]}")
    arm_serving_faults(workdir, plan_json)

    model = build_model(cfg)
    prefix_on = bool(cfg.get("prefix_cache", 0))
    engine = ServingEngine(
        model, block_size=cfg["block_size"], num_blocks=cfg["num_blocks"],
        max_batch=cfg["max_batch"], max_seq_len=cfg["max_pos"],
        journal=journal, prefix_cache=prefix_on)
    pending = [r for r in trace if r.rid in pending_rids]
    if prefix_on:
        # group shared prefixes adjacently (by prompt, so the journal
        # hash groups identical prompts too): replayed sharers re-attach
        # to the pages the first of them re-prefills instead of each
        # re-prefilling cold
        pending.sort(key=lambda r: tuple(int(t) for t in r.prompt_ids))
    engine.serve(pending)
    # clean exit: stamp the closed=true farewell snapshot so the fleet
    # view reads "exited", not (eventually) "dead" — only a SIGKILLed
    # incarnation goes silent without one
    live.disarm(final_export=True)
    return 0


def main():
    workdir = os.environ["SERVE_WORK_DIR"]
    cfg = json.loads(os.environ["SERVE_CFG"])
    return run(workdir, cfg, os.environ.get("SERVE_PLAN", ""))


if __name__ == "__main__":
    sys.exit(main())
