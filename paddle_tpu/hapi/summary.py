"""paddle.summary (ref: python/paddle/hapi/model_summary.py).

Runs a forward pass with forward-post hooks capturing each leaf layer's
output shape, then prints the familiar layer table and returns
{'total_params', 'trainable_params'}.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import to_dtype
from ..nn.layer import Layer

__all__ = ["summary"]


def _leaf_layers(model: Layer):
    for name, layer in model.named_sublayers(include_self=False):
        if not list(layer.sublayers(include_self=False)):
            yield name, layer


def _n_params(layer: Layer):
    total = trainable = 0
    for ref in layer.parameters():
        n = int(np.prod(ref.shape))
        total += n
        if ref.trainable:
            trainable += n
    return total, trainable


def _shapes(out):
    leaves = jax.tree_util.tree_leaves(out)
    return ", ".join(str(list(x.shape)) for x in leaves
                     if hasattr(x, "shape"))


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table for one forward pass.

    ``input_size``: tuple (or list of tuples) incl. batch dim — -1 batch
    becomes 1, matching the reference; or pass a ready ``input`` tensor.
    """
    if input is None:
        if input_size is None:
            raise ValueError("summary() needs input_size or input")
        sizes = [input_size] if isinstance(input_size[0], int) else \
            list(input_size)
        if dtypes is None:
            dtypes_list = ["float32"] * len(sizes)
        elif isinstance(dtypes, (list, tuple)):
            dtypes_list = list(dtypes)
        else:
            dtypes_list = [dtypes] * len(sizes)
        inputs = [
            jnp.zeros([1 if d == -1 else d for d in size],
                      dtype=to_dtype(dt))
            for size, dt in zip(sizes, dtypes_list)
        ]
    else:
        inputs = [input] if not isinstance(input, (list, tuple)) else \
            list(input)

    rows = []
    handles = []

    def make_hook(name, layer):
        def hook(lyr, inp, out):
            total, _ = _n_params(lyr)
            rows.append((f"{type(lyr).__name__} ({name})", _shapes(out),
                         total))
        return hook

    was_training = net.training
    net.eval()
    for name, layer in _leaf_layers(net):
        handles.append(layer.register_forward_post_hook(
            make_hook(name, layer)))
    try:
        net(*inputs)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total_params = trainable_params = 0
    for ref in net.parameters():
        n = int(np.prod(ref.shape))
        total_params += n
        if ref.trainable:
            trainable_params += n

    w_layer = max([len(r[0]) for r in rows] + [20]) + 2
    w_shape = max([len(r[1]) for r in rows] + [14]) + 2
    header = (f"{'Layer (type)':{w_layer}s}{'Output Shape':{w_shape}s}"
              f"{'Param #':>12s}")
    sep = "-" * len(header)
    lines = [sep, header, sep]
    for name, shape, n in rows:
        lines.append(f"{name:{w_layer}s}{shape:{w_shape}s}{n:>12,d}")
    lines += [sep,
              f"Total params: {total_params:,}",
              f"Trainable params: {trainable_params:,}",
              f"Non-trainable params: {total_params - trainable_params:,}",
              sep]
    print("\n".join(lines))
    return {"total_params": total_params,
            "trainable_params": trainable_params}
