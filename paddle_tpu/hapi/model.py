"""High-level Model API.

Parity with ``python/paddle/hapi/model.py:1050`` (``Model``; ``fit`` at
``:1752``; DynamicGraphAdapter.train_batch at ``:817``).

TPU-native design: instead of an eager per-op loop, ``prepare()`` builds ONE
jitted train step over the functional view of (params, buffers, opt_state,
scaler_state, batch, lr, rng_key). XLA compiles forward+backward+optimizer
into a single fused program per batch signature — this is the reference's
"static graph mode" performance with dygraph UX, and is exactly the step the
distributed wrappers shard via pjit. AMP is handled inside the step (policy
casts under auto_cast; optional fp16 loss scaling with found_inf masking).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..amp.auto_cast import auto_cast
from ..amp.grad_scaler import GradScaler, unscale_and_check
from ..core.random import rng_scope, default_generator
from ..framework.functional import (functional_call, get_buffers, get_params,
                                    set_buffers, set_params)
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer
from ..profiler.monitor import stat_add
from .callbacks import config_callbacks

__all__ = ["Model"]


def _as_tuple(x):
    if x is None:
        return ()
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer: Optional[Optimizer] = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._amp_level = "O0"
        self._amp_custom_lists = {}
        self._scaler: Optional[GradScaler] = None
        self._train_step_fn = None
        self._eval_step_fn = None
        self._predict_fn = None
        self._grad_step_fn = None
        self._apply_step_fn = None
        self._opt_state = None
        self._scaler_state = None
        self._step_count = 0
        self._accum_grads = None
        self._accum_count = 0
        self._accum_found_inf = None

    # -- setup ---------------------------------------------------------------

    def prepare(self, optimizer: Optional[Optimizer] = None, loss=None,
                metrics: Optional[Sequence[Metric]] = None,
                amp_configs: Union[None, str, Dict] = None) -> None:
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = list(metrics or [])
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
            self._amp_custom_lists = {
                k: amp_configs[k] for k in
                ("custom_white_list", "custom_black_list") if k in amp_configs}
            if self._amp_level != "O0" and amp_configs.get("use_fp16_guard") is None:
                pass
        if self._amp_level == "O2":
            from ..amp.auto_cast import decorate
            decorate(self.network, level="O2")
        self._train_step_fn = None  # force rebuild
        self._eval_step_fn = None
        self._grad_step_fn = None
        self._apply_step_fn = None

    # -- functional step builders ---------------------------------------------

    def _loss_value(self, outputs, labels):
        losses = self._loss(*_as_tuple(outputs), *_as_tuple(labels))
        total = sum(jnp.sum(l) for l in _as_tuple(losses)) \
            if isinstance(losses, (tuple, list)) else losses
        return total, losses

    def _make_grads_fn(self):
        """Shared gradient-computation closure (AMP autocast, loss scaling,
        unscale + inf check) used by both the fused train step and the
        accumulation grad step."""
        net = self.network
        amp_level = self._amp_level
        amp_lists = self._amp_custom_lists
        use_scaler = self._scaler is not None and self._scaler.is_enable()

        def grads_of(params, buffers, scaler_state, inputs, labels, key):
            trainable = {k: v for k, v in params.items()
                         if k in self._trainable_names}
            frozen = {k: v for k, v in params.items()
                      if k not in self._trainable_names}

            def loss_fn(tp):
                full = {**tp, **frozen}
                with rng_scope(key):
                    if amp_level in ("O1", "O2"):
                        with auto_cast(enable=True, level=amp_level,
                                       **amp_lists):
                            out, new_buf = functional_call(
                                net, full, *inputs, buffers=buffers,
                                mutable=True, training=True)
                    else:
                        out, new_buf = functional_call(
                            net, full, *inputs, buffers=buffers,
                            mutable=True, training=True)
                total, _ = self._loss_value(out, labels)
                scaled = (total * scaler_state["scale"].astype(total.dtype)
                          if use_scaler else total)
                return scaled, (total, out, new_buf)

            grads, (total, out, new_buf) = jax.grad(
                loss_fn, has_aux=True)(trainable)
            if use_scaler:
                grads, found_inf = unscale_and_check(
                    grads, scaler_state["scale"])
            else:
                found_inf = jnp.asarray(False)
            return trainable, frozen, grads, total, out, new_buf, found_inf

        return grads_of

    def _build_train_step(self):
        opt = self._optimizer
        use_scaler = self._scaler is not None and self._scaler.is_enable()
        scaler = self._scaler
        grads_of = self._make_grads_fn()

        def step(params, buffers, opt_state, scaler_state, inputs, labels,
                 lr, key):
            (trainable, frozen, grads, total, out, new_buf,
             found_inf) = grads_of(params, buffers, scaler_state, inputs,
                                   labels, key)
            # FLAGS_check_nan_inf (ref nan_inf_utils.h:38) — the shared
            # fault/health scan entry
            from ..fault import health as _health
            _health.check_numerics(loss=total, grads=grads,
                                   where="Model.train_batch")
            if use_scaler:
                new_scaler_state = scaler.update_state(scaler_state, found_inf)
            else:
                new_scaler_state = scaler_state

            new_trainable, new_opt_state = opt.apply_gradients(
                trainable, grads, opt_state, lr)
            # Skip the update when grads overflowed (fp16 mode).
            if use_scaler:
                new_trainable = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(found_inf, old, new),
                    new_trainable, trainable)
                new_opt_state = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(found_inf, old, new),
                    new_opt_state, opt_state)
            # also scan the optimizer state pytree (moments can go NaN a
            # step after the grads did and survive the skip)
            _health.check_numerics(opt_state=new_opt_state,
                                   where="Model.train_batch")
            new_params = {**new_trainable, **frozen}
            return (new_params, new_buf, new_opt_state, new_scaler_state,
                    total, out)

        return jax.jit(step, donate_argnums=(0, 1, 2, 3))

    def _build_grad_step(self):
        """Gradient-only step for accumulation (update=False): returns
        unscaled grads without touching optimizer state."""
        grads_of = self._make_grads_fn()

        def step(params, buffers, scaler_state, inputs, labels, key):
            (_, _, grads, total, _, new_buf,
             found_inf) = grads_of(params, buffers, scaler_state, inputs,
                                   labels, key)
            return grads, new_buf, total, found_inf

        return jax.jit(step)

    def _build_apply_step(self):
        """Apply pre-accumulated grads (the final micro-batch of an
        accumulation window)."""
        opt = self._optimizer
        scaler = self._scaler
        use_scaler = scaler is not None and scaler.is_enable()

        def step(params, opt_state, scaler_state, grads, lr, denom,
                 found_inf):
            trainable = {k: v for k, v in params.items()
                         if k in self._trainable_names}
            frozen = {k: v for k, v in params.items()
                      if k not in self._trainable_names}
            grads = jax.tree_util.tree_map(lambda g: g / denom, grads)
            new_trainable, new_opt_state = opt.apply_gradients(
                trainable, grads, opt_state, lr)
            if use_scaler:
                new_scaler_state = scaler.update_state(scaler_state, found_inf)
                new_trainable = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(found_inf, old, new),
                    new_trainable, trainable)
                new_opt_state = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(found_inf, old, new),
                    new_opt_state, opt_state)
            else:
                new_scaler_state = scaler_state
            return {**new_trainable, **frozen}, new_opt_state, new_scaler_state

        return jax.jit(step)

    def _build_eval_step(self):
        net = self.network

        def step(params, buffers, inputs, labels):
            out = functional_call(net, params, *inputs, buffers=buffers,
                                  training=False)
            total, losses = self._loss_value(out, labels) \
                if self._loss is not None else (None, None)
            return total, out

        return jax.jit(step)

    # -- batch-level API -------------------------------------------------------

    @property
    def _trainable_names(self):
        return {name for name, ref in self.network.named_parameters()
                if ref.trainable}

    def _ensure_state(self):
        params = get_params(self.network)
        if self._opt_state is None:
            trainable = {k: v for k, v in params.items()
                         if k in self._trainable_names}
            self._opt_state = self._optimizer.init(trainable)
        if self._scaler_state is None:
            self._scaler_state = (self._scaler.init_state() if self._scaler
                                  else {"scale": jnp.ones((), jnp.float32)})

    def train_batch(self, inputs, labels=None, update: bool = True):
        """One optimizer step on a batch; returns loss (ref train_batch :817)."""
        assert self._optimizer is not None and self._loss is not None, \
            "call prepare(optimizer, loss) first"
        stat_add("model.train_batches")
        inputs = tuple(jnp.asarray(x) for x in _as_tuple(inputs))
        labels = tuple(jnp.asarray(y) for y in _as_tuple(labels))
        self._ensure_state()
        params = get_params(self.network)
        buffers = get_buffers(self.network)
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        key = default_generator().next_key()

        from ..observability import step_monitor
        tm = step_monitor.current()

        def _dispatch_phase(kind):
            # Recompile sentinel: churn comes from the (inputs, labels)
            # signature; the first dispatch of a signature is "compile".
            if not tm.enabled:
                return "device"
            return tm.observe_dispatch(
                (f"Model.{kind}", id(self)), (inputs, labels),
                where=f"hapi.Model.{kind}")

        accumulating = (not update) or self._accum_grads is not None
        if not accumulating:
            # Fast path: fused grad+apply, donated state.
            if self._train_step_fn is None:
                self._train_step_fn = self._build_train_step()
            with tm.phase(_dispatch_phase("train_batch")):
                (new_params, new_buffers, self._opt_state,
                 self._scaler_state, loss, out) = self._train_step_fn(
                    params, buffers, self._opt_state, self._scaler_state,
                    inputs, labels, lr, key)
            set_params(self.network, new_params)
            set_buffers(self.network, new_buffers)
            self._step_count += 1
            return np.asarray(loss)

        # Accumulation path (update=False micro-batches, then update=True).
        if self._grad_step_fn is None:
            self._grad_step_fn = self._build_grad_step()
        with tm.phase(_dispatch_phase("grad_batch")):
            grads, new_buffers, loss, found_inf = self._grad_step_fn(
                params, buffers, self._scaler_state, inputs, labels, key)
        set_buffers(self.network, new_buffers)
        if self._accum_grads is None:
            self._accum_grads, self._accum_count = grads, 1
            self._accum_found_inf = found_inf
        else:
            self._accum_grads = jax.tree_util.tree_map(
                jnp.add, self._accum_grads, grads)
            self._accum_count += 1
            self._accum_found_inf = jnp.logical_or(
                self._accum_found_inf, found_inf)
        if update:
            self._flush_accumulated()
        return np.asarray(loss)

    def _flush_accumulated(self) -> None:
        """Apply any pending accumulated gradients (end of an accumulation
        window, or a partial window at epoch/train end)."""
        if self._accum_grads is None:
            return
        if self._apply_step_fn is None:
            self._apply_step_fn = self._build_apply_step()
        params = get_params(self.network)
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        denom = jnp.asarray(float(self._accum_count), jnp.float32)
        new_params, self._opt_state, self._scaler_state = \
            self._apply_step_fn(params, self._opt_state,
                                self._scaler_state, self._accum_grads,
                                lr, denom, self._accum_found_inf)
        set_params(self.network, new_params)
        self._accum_grads = None
        self._accum_count = 0
        self._accum_found_inf = None
        self._step_count += 1

    def eval_batch(self, inputs, labels=None):
        inputs = tuple(jnp.asarray(x) for x in _as_tuple(inputs))
        labels = tuple(jnp.asarray(y) for y in _as_tuple(labels))
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        params = get_params(self.network)
        buffers = get_buffers(self.network)
        loss, out = self._eval_step_fn(params, buffers, inputs, labels)
        return (np.asarray(loss) if loss is not None else None), out

    def predict_batch(self, inputs):
        inputs = tuple(jnp.asarray(x) for x in _as_tuple(inputs))
        if self._predict_fn is None:
            net = self.network

            def fwd(params, buffers, inputs):
                return functional_call(net, params, *inputs, buffers=buffers,
                                       training=False)

            self._predict_fn = jax.jit(fwd)
        out = self._predict_fn(get_params(self.network),
                               get_buffers(self.network), inputs)
        return out

    # -- loops -----------------------------------------------------------------

    def _to_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch, n_labels_hint: int = 1):
        batch = _as_tuple(batch)
        if len(batch) == 1:
            return batch, ()
        return batch[:-n_labels_hint], batch[-n_labels_hint:]

    def fit(self, train_data=None, eval_data=None, batch_size: int = 1,
            epochs: int = 1, eval_freq: int = 1, log_freq: int = 10,
            save_dir: Optional[str] = None, save_freq: int = 1,
            verbose: int = 1, drop_last: bool = False, shuffle: bool = True,
            num_workers: int = 0, callbacks=None, accumulate_grad_batches=1,
            num_iters: Optional[int] = None) -> None:
        """ref: hapi/model.py:1752."""
        loader = self._to_loader(train_data, batch_size, shuffle, num_workers,
                                 drop_last)
        eval_loader = self._to_loader(eval_data, batch_size, False,
                                      num_workers, False)
        cbks = config_callbacks(callbacks, model=self, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics)
        self.stop_training = False
        from ..observability import step_monitor
        tm = step_monitor.current()
        cbks.on_train_begin()
        iters_done = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            logs: Dict[str, Any] = {}
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                with tm.step():
                    with tm.phase("callbacks"):
                        cbks.on_train_batch_begin(step)
                    inputs, labels = self._split_batch(batch)
                    update = (step + 1) % max(1, accumulate_grad_batches) == 0
                    loss = self.train_batch(inputs, labels, update=update)
                    logs["loss"] = loss
                    logs["lr"] = self._optimizer.get_lr()
                    with tm.phase("callbacks"):
                        cbks.on_train_batch_end(step, logs)
                iters_done += 1
                if num_iters is not None and iters_done >= num_iters:
                    self.stop_training = True
                    break
            # Partial accumulation window at epoch end: apply it rather than
            # leaking micro-batch grads into the next epoch (or dropping them
            # at train end).
            self._flush_accumulated()
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _callbacks=cbks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
        cbks.on_train_end(logs if "logs" in dir() else None)

    def evaluate(self, eval_data, batch_size: int = 1, log_freq: int = 10,
                 verbose: int = 1, num_workers: int = 0, callbacks=None,
                 num_samples: Optional[int] = None, _callbacks=None) -> Dict[str, Any]:
        loader = self._to_loader(eval_data, batch_size, False, num_workers, False)
        cbks = _callbacks or config_callbacks(callbacks, model=self,
                                              verbose=verbose)
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            loss, out = self.eval_batch(inputs, labels)
            if loss is not None:
                losses.append(float(np.asarray(loss)))
            for m in self._metrics:
                args = m.compute(*_as_tuple(out), *labels)
                m.update(*_as_tuple(args))
            cbks.on_eval_batch_end(step)
        logs: Dict[str, Any] = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size: int = 1, num_workers: int = 0,
                stack_outputs: bool = False, verbose: int = 1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False, num_workers, False)
        outputs = []
        for batch in loader:
            inputs = _as_tuple(batch)
            out = self.predict_batch(inputs)
            outputs.append(np.asarray(out))
        if stack_outputs:
            return np.concatenate(outputs, axis=0)
        return outputs

    # -- persistence ------------------------------------------------------------

    def parameters(self):
        return self.network.parameters()

    def state_dict(self):
        return self.network.state_dict()

    def save(self, path: str, training: bool = True) -> None:
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            # Persist functional opt state in paddle's name@key format.
            opt_state = {"step": self._opt_state["step"]} if self._opt_state else {}
            if self._opt_state:
                for pname, st in self._opt_state["param_states"].items():
                    for k, v in st.items():
                        opt_state[f"{pname}@{k}"] = v
            sched = self._optimizer.lr_scheduler
            if sched is not None:
                opt_state["LR_Scheduler"] = sched.state_dict()
            fsave(opt_state, path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer: bool = False):
        from ..framework.io import load as fload
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path) and self._optimizer:
            raw = fload(opt_path)
            sched_state = raw.pop("LR_Scheduler", None)
            if sched_state and self._optimizer.lr_scheduler:
                self._optimizer.lr_scheduler.set_state_dict(sched_state)
            step = raw.pop("step", 0)
            pstates: Dict[str, Dict[str, Any]] = {}
            for key, v in raw.items():
                pname, _, k = key.rpartition("@")
                pstates.setdefault(pname, {})[k] = jnp.asarray(v)
            if pstates:
                self._opt_state = {"step": jnp.asarray(step, jnp.int32),
                                   "param_states": pstates}

    def summary(self, input_size=None, dtype=None):
        if input_size is not None:
            from .summary import summary as _summary
            return _summary(self.network, input_size, dtypes=dtype)
        # no input shape: parameter table only
        n, e = 0, 0
        lines = []
        for name, ref in self.network.named_parameters():
            n += 1
            e += int(np.prod(ref.shape))
            lines.append(f"{name:60s} {str(ref.shape):20s} {str(ref.dtype)}")
        print("\n".join(lines) + f"\nTotal params: {e:,} ({n} tensors)")
        return {"total_params": e, "trainable_params": e}
