"""Training callbacks (ref: python/paddle/hapi/callbacks.py — ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping)."""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRSchedulerCallback",
           "EarlyStopping", "StatsLoggerCallback", "config_callbacks",
           "CallbackList"]


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq: int = 10, verbose: int = 1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._steps = 0
        self._epoch_t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            dt = time.time() - self._epoch_t0
            print(f"Epoch {self.epoch} step {step}: {items} "
                  f"({self._steps / max(dt, 1e-9):.1f} steps/s)")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done: {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Eval: {items}")


def _fmt(v):
    try:
        arr = np.asarray(v)
        if arr.size == 1:
            return f"{float(arr):.6g}"
        return np.array2string(arr, precision=4)
    except Exception:
        return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRSchedulerCallback(Callback):
    """Steps the optimizer's LRScheduler (by epoch by default, per-batch if
    by_step)."""

    def __init__(self, by_step: bool = False, by_epoch: bool = True):
        self.by_step = by_step
        self.by_epoch = by_epoch and not by_step
        self._last_step_count = None

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt.lr_scheduler if opt is not None else None

    def on_train_begin(self, logs=None):
        self._last_step_count = getattr(self.model, "_step_count", None)

    def on_train_batch_end(self, step, logs=None):
        sched = self._sched()
        if self.by_step and sched is not None:
            # Step per *optimizer update*, not per micro-batch: under
            # gradient accumulation only batches that applied an update
            # advance the schedule.
            count = getattr(self.model, "_step_count", None)
            if count is None or count != self._last_step_count:
                sched.step()
                self._last_step_count = count

    def on_epoch_end(self, epoch, logs=None):
        sched = self._sched()
        if self.by_epoch and sched is not None:
            sched.step()


class StatsLoggerCallback(Callback):
    """Per-epoch stat snapshots in the training log + a periodic
    ``StatsReporter`` for long epochs (ref: the reference's monitor/stat
    registry feeding the per-rank worker logs). Installed by
    ``config_callbacks`` whenever ``FLAGS_telemetry`` != ``off``; the old
    construct-but-never-start gap is closed here — ``fit`` owns the
    reporter's lifecycle."""

    def __init__(self, interval: float = 60.0, logger=None):
        from ..profiler.monitor import get_logger
        self.interval = interval
        self.logger = logger or get_logger("paddle_tpu.monitor")
        self._reporter = None

    def on_train_begin(self, logs=None):
        from ..profiler.monitor import StatsReporter
        if self._reporter is None:
            self._reporter = StatsReporter(self.interval, logger=self.logger)
        self._reporter.start()

    def on_epoch_end(self, epoch, logs=None):
        from ..observability import metrics
        snap = metrics.stats_snapshot()
        if snap:
            self.logger.info("epoch %d stats %s", epoch, snap)

    def on_train_end(self, logs=None):
        if self._reporter is not None:
            self._reporter.stop()


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, verbose: int = 1, min_delta: float = 0,
                 baseline=None, save_best_model: bool = True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).reshape(-1)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


def config_callbacks(callbacks=None, model=None, log_freq: int = 10,
                     verbose: int = 1, save_freq: int = 1, save_dir=None,
                     metrics=None) -> CallbackList:
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks.insert(0, ProgBarLogger(log_freq, verbose))
    if not any(isinstance(c, LRSchedulerCallback) for c in cbks):
        cbks.append(LRSchedulerCallback())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    from ..observability.trace import telemetry_mode
    if telemetry_mode() != "off" and \
            not any(isinstance(c, StatsLoggerCallback) for c in cbks):
        cbks.append(StatsLoggerCallback())
    cl = CallbackList(cbks)
    if model is not None:
        cl.set_model(model)
    return cl
