"""paddle.fft parity (ref: python/paddle/fft.py over fft_c2c/fft_r2c/
fft_c2r kernels, phi/kernels/fft_kernel.h).

On TPU, FFTs lower to XLA's FftOp directly from jnp.fft — the reference's
three specialized kernels (c2c/r2c/c2r) are dispatch detail XLA handles
internally. `norm` semantics ("backward"/"ortho"/"forward") match numpy's,
which is what the reference exposes.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "hfft2", "ihfft2", "hfftn", "ihfftn","fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D Hermitian FFT (ref paddle.fft.hfft2): hfft over the last axis
    after an inverse-signal FFT over the first."""
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D Hermitian-input FFT: ifftn over all but the last axis, hfft on
    the last (numpy/scipy's definition; ref fft.py hfftn)."""
    x = jnp.asarray(x)
    if axes is None:
        axes = tuple(range(x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    if s is None:
        s = [2 * (x.shape[a] - 1) if a == axes[-1] else x.shape[a]
             for a in axes]
    out = x
    for a, n in zip(axes[:-1], s[:-1]):
        out = jnp.fft.ifft(out, n=n, axis=a, norm=norm)
    return jnp.fft.hfft(out, n=s[-1], axis=axes[-1], norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn (ref fft.py ihfftn)."""
    x = jnp.asarray(x)
    if axes is None:
        axes = tuple(range(x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    if s is None:
        s = [x.shape[a] for a in axes]
    out = jnp.fft.ihfft(x, n=s[-1], axis=axes[-1], norm=norm)
    for a, n in zip(axes[:-1], s[:-1]):
        out = jnp.fft.fft(out, n=n, axis=a, norm=norm)
    return out
