"""paddle.fft parity (ref: python/paddle/fft.py over fft_c2c/fft_r2c/
fft_c2r kernels, phi/kernels/fft_kernel.h).

On TPU, FFTs lower to XLA's FftOp directly from jnp.fft — the reference's
three specialized kernels (c2c/r2c/c2r) are dispatch detail XLA handles
internally. `norm` semantics ("backward"/"ortho"/"forward") match numpy's,
which is what the reference exposes.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)
