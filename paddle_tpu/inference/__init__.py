"""Inference predictor API.

Reference design: ``paddle_infer::CreatePredictor(config)`` →
``AnalysisPredictor`` (``paddle/fluid/inference/api/analysis_predictor.h:94``)
— load saved program+params, run the Analyzer IR pass pipeline (fusion,
mixed precision, memory optim per ``api/paddle_pass_builder.cc``), then
execute per-run: copy inputs → executor → fetch outputs through named
handles.

TPU-native design: the saved model is a serialized StableHLO export
(``paddle_tpu.jit.save``); "analysis passes" are XLA's compilation (fusion /
layout / memory optimization happen in the compiler, so the pass-pipeline
surface reduces to compile options), and the per-run path is an AOT-compiled
executable call. The named-handle copy_from_cpu/run/copy_to_cpu protocol is
kept verbatim so reference users can port serving code unchanged.

Ragged traffic: a model exported with symbolic dims (``jit.save`` with
``None``/named dims in ``input_spec``) accepts any size on those dims —
but every distinct concrete size pays a full XLA compile at call time,
silently. ``Predictor.run`` therefore pads every symbolic dim up to a
registered bucket (power-of-two ladder by default,
``Config.set_shape_buckets`` to override), slices the outputs back via
the export's shape-polymorphic output avals, and announces the bucket
set once through the analysis Diagnostic channel (rule O004). A
:class:`~paddle_tpu.observability.RecompileSentinel` watches the padded
dispatch signatures, so a bucketing failure surfaces as O001 instead of
a silent compile storm.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics
from ..observability.step_monitor import RecompileSentinel
from ..serving.buckets import BucketSet, pad_axis

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PredictorBenchmark"]

# Compile budget the predictor's recompile sentinel tolerates before an
# O001 churn Diagnostic fires: with the default power-of-two ladder a
# trace spanning sizes 1..2^k hits k+1 buckets, so 16 distinct padded
# signatures means bucketing is NOT working (or the operator registered
# an unusually wide explicit set — then set_shape_buckets sizes the
# budget).
DEFAULT_COMPILE_BUDGET = 16


class Config:
    """ref: paddle_infer.Config (api/paddle_analysis_config.h). Holds the
    model path + execution options; GPU/TensorRT/MKLDNN toggles are accepted
    for API compatibility and mapped to their TPU/XLA meaning (or ignored
    where XLA always does the optimization)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # jit.save writes <path>.pdmodel/<path>.pdiparams; accept either the
        # bare prefix or the .pdmodel path.
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._ir_optim = True
        self._memory_optim = True
        self._device = "tpu"
        self._precision = None  # None = saved dtype; "bf16" casts params
        self._cpu_threads = 1
        self._shape_buckets: Optional[Sequence[int]] = None

    def set_shape_buckets(self, sizes: Sequence[int]):
        """Register the bucket sizes symbolic input dims are padded to
        (default: a growing power-of-two ladder). The list length is the
        predictor's compile budget."""
        self._shape_buckets = [int(s) for s in sizes]

    def shape_buckets(self) -> Optional[Sequence[int]]:
        return None if self._shape_buckets is None \
            else list(self._shape_buckets)

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file

    def model_dir(self) -> Optional[str]:
        return self._prefix

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag  # XLA always optimizes; kept for parity

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        self._device = "accelerator"  # any accelerator == default backend

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n: int):
        self._cpu_threads = n

    def enable_low_precision(self, dtype: str = "bf16"):
        """TPU analog of enable_use_gpu(precision=half)/TensorRT fp16."""
        self._precision = dtype

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix!r}, device={self._device}, "
                f"precision={self._precision or 'saved'})")


class Tensor:
    """Named input/output handle (ref: paddle_infer.Tensor /
    ZeroCopyTensor). copy_from_cpu stages a host array; copy_to_cpu
    materializes the device result."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.asarray(arr)

    def reshape(self, shape: Sequence[int]):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"output {self.name!r} not populated — "
                               "call predictor.run() first")
        return np.asarray(self._value)

    @property
    def shape(self):
        return None if self._value is None else tuple(self._value.shape)


class Predictor:
    """ref AnalysisPredictor: named-handle run protocol over the AOT
    executable."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        if not config.model_dir():
            raise ValueError("Config has no model path")
        self._config = config
        self._translated = jit_load(config.model_dir())
        n_in = self._n_model_inputs()
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs: Dict[str, Tensor] = {n: Tensor(n)
                                           for n in self._input_names}
        self._outputs: Dict[str, Tensor] = {}
        self._output_names: List[str] = []
        # -- symbolic-dim bucketing state -----------------------------------
        exported = self._translated._exported
        self._model_in_avals = tuple(exported.in_avals[-n_in:])
        self._out_avals = tuple(exported.out_avals)
        self._sym_vars: List[str] = sorted({
            str(d) for aval in self._model_in_avals
            for d in aval.shape if not isinstance(d, int)})
        explicit = config.shape_buckets()
        self._buckets = BucketSet(explicit, grow=False) \
            if explicit else BucketSet([1], grow=True)
        budget = len(explicit) if explicit else DEFAULT_COMPILE_BUDGET
        self._sentinel = RecompileSentinel(threshold=budget)
        self._padded_signatures: set = set()
        self.diagnostics: List[Any] = []
        self._announced = False

    def _n_model_inputs(self) -> int:
        # Exported calling convention: (params_tree, buffers_tree, *xs).
        exported = self._translated._exported
        tree = exported.in_tree
        # in_tree is ((args...), kwargs); args = (params, buffers, *xs)
        n_args = tree.num_leaves  # leaves include params/buffers
        n_pb = (len(jax.tree_util.tree_leaves(self._translated._params)) +
                len(jax.tree_util.tree_leaves(self._translated._buffers)))
        # Remaining leaves are the example inputs.
        return max(1, n_args - n_pb)

    # -- handle protocol ---------------------------------------------------

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    # -- symbolic-dim bucket padding ----------------------------------------

    def _dim_assignment(self, xs: List[np.ndarray]) -> Dict[str, int]:
        """Concrete size of every symbolic dim var, from the staged
        inputs (consistency across shared vars enforced)."""
        assign: Dict[str, int] = {}
        for aval, x in zip(self._model_in_avals, xs):
            if len(aval.shape) != x.ndim:
                raise ValueError(
                    f"input rank {x.ndim} does not match exported rank "
                    f"{len(aval.shape)} ({aval.shape})")
            for axis, d in enumerate(aval.shape):
                if isinstance(d, int):
                    continue
                name, size = str(d), int(x.shape[axis])
                if assign.setdefault(name, size) != size:
                    raise ValueError(
                        f"symbolic dim {name!r} bound to both "
                        f"{assign[name]} and {size}")
        return assign

    def _announce_buckets(self) -> None:
        """One-time Diagnostic (rule O004, analysis channel) stating the
        bucket set — the predictor's compile budget in plain sight."""
        if self._announced:
            return
        self._announced = True
        from ..analysis import jaxpr_lint
        d = jaxpr_lint.Diagnostic(
            rule="O004", name="shape-bucket-set",
            severity=jaxpr_lint.INFO,
            message=(f"symbolic input dims {self._sym_vars} are padded to "
                     f"registered buckets {self._buckets.sizes}"
                     f"{' (power-of-two ladder, grows)' if self._buckets.grow else ''}"
                     f" — at most {self._sentinel.threshold} distinct "
                     "compiled signatures before O001 fires"),
            where="inference.Predictor",
            hint="set_shape_buckets() on the Config pins an explicit set "
                 "(and the compile budget) for production traffic")
        self.diagnostics.append(d)
        try:
            jaxpr_lint.emit([d], where=d.where)
        except Exception:
            pass

    def _pad_to_buckets(self, xs: List[np.ndarray]
                        ) -> Tuple[List[np.ndarray], Dict[str, int],
                                   Dict[str, int]]:
        assign = self._dim_assignment(xs)
        padded = {n: self._buckets.fit(v) for n, v in assign.items()}
        out = []
        for aval, x in zip(self._model_in_avals, xs):
            for axis, d in enumerate(aval.shape):
                if not isinstance(d, int) and str(d) in padded:
                    x = pad_axis(x, axis, padded[str(d)])
            out.append(x)
        return out, assign, padded

    def _slice_outputs(self, flat: List[np.ndarray],
                       assign: Dict[str, int]) -> List[np.ndarray]:
        """Undo the bucket padding on outputs: any output axis whose
        exported aval dim is a bare symbolic var is sliced back to that
        var's original size (derived expressions like ``2*b`` pass
        through padded)."""
        out = []
        for aval, x in zip(self._out_avals, flat):
            x = np.asarray(x)
            for axis, d in enumerate(aval.shape):
                if not isinstance(d, int) and str(d) in assign:
                    x = x[(slice(None),) * axis +
                          (slice(0, assign[str(d)]),)]
            out.append(x)
        return out

    def bucket_report(self) -> Dict[str, Any]:
        """Distinct padded signatures dispatched (== compiled
        executables) and the live bucket set."""
        return {"compiles": len(self._padded_signatures),
                "buckets": self._buckets.sizes,
                "budget": self._sentinel.threshold,
                "o001_fired": bool(self._sentinel.diagnostics)}

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute. Either pass arrays positionally (returns outputs like
        the reference's predictor.run(inputs) overload) or stage them via
        get_input_handle(...).copy_from_cpu(...) first. Symbolic-dim
        exports are padded to the registered shape buckets (outputs
        sliced back), bounding compiles at the bucket-set size."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        xs = []
        for n in self._input_names:
            v = self._inputs[n]._value
            if v is None:
                raise RuntimeError(f"input {n!r} not set")
            xs.append(np.asarray(v))
        assign: Dict[str, int] = {}
        if self._sym_vars:
            xs, assign, _ = self._pad_to_buckets(xs)
            self._announce_buckets()
            sig = tuple((x.shape, str(x.dtype)) for x in xs)
            self._padded_signatures.add(sig)
            self._sentinel.observe_tree("inference.Predictor.run",
                                        tuple(xs),
                                        where="inference.Predictor.run")
        out = self._translated(*[jnp.asarray(x) for x in xs])
        flat = jax.tree_util.tree_leaves(out)
        if assign:
            flat = self._slice_outputs(flat, assign)
        self._output_names = [f"out{i}" for i in range(len(flat))]
        self._outputs = {}
        for n, v in zip(self._output_names, flat):
            t = Tensor(n)
            t.copy_from_cpu(np.asarray(v))
            self._outputs[n] = t
        if inputs is not None:
            return [self._outputs[n].copy_to_cpu()
                    for n in self._output_names]
        return True

    def clear_intermediate_tensor(self):
        pass  # XLA manages buffers

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    """ref: paddle_infer::CreatePredictor."""
    return Predictor(config)


class PredictorBenchmark:
    """Latency micro-bench (ref fluid/inference/utils/benchmark.h).

    Reports through the shared observability metrics registry — each
    timed run feeds the ``serving.predictor_latency_ms`` histogram and
    sets the ``serving.predictor_qps`` gauge — instead of keeping ad-hoc
    timing fields; the returned ``latency_ms``/``qps`` keys are forwards
    of what this run contributed to the registry."""

    def __init__(self, predictor: Predictor):
        self.predictor = predictor
        self._hist = metrics.histogram(
            "serving.predictor_latency_ms",
            "one-shot Predictor.run wall time (ms)").labels()
        self._qps = metrics.gauge(
            "serving.predictor_qps",
            "one-shot Predictor.run throughput (last bench)").labels()

    def run(self, inputs: Sequence[np.ndarray], warmup: int = 2,
            repeat: int = 10) -> Dict[str, float]:
        for _ in range(warmup):
            self.predictor.run(list(inputs))
        before = self._hist.get()
        for _ in range(repeat):
            t0 = time.perf_counter()
            self.predictor.run(list(inputs))
            self._hist.observe((time.perf_counter() - t0) * 1e3)
        after = self._hist.get()
        n = max(after["count"] - before["count"], 1)
        lat_ms = (after["sum"] - before["sum"]) / n
        qps = 1e3 / lat_ms if lat_ms else 0.0
        self._qps.set(qps)
        return {"latency_ms": lat_ms, "qps": qps}
