"""Inference predictor API.

Reference design: ``paddle_infer::CreatePredictor(config)`` →
``AnalysisPredictor`` (``paddle/fluid/inference/api/analysis_predictor.h:94``)
— load saved program+params, run the Analyzer IR pass pipeline (fusion,
mixed precision, memory optim per ``api/paddle_pass_builder.cc``), then
execute per-run: copy inputs → executor → fetch outputs through named
handles.

TPU-native design: the saved model is a serialized StableHLO export
(``paddle_tpu.jit.save``); "analysis passes" are XLA's compilation (fusion /
layout / memory optimization happen in the compiler, so the pass-pipeline
surface reduces to compile options), and the per-run path is an AOT-compiled
executable call. The named-handle copy_from_cpu/run/copy_to_cpu protocol is
kept verbatim so reference users can port serving code unchanged.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PredictorBenchmark"]


class Config:
    """ref: paddle_infer.Config (api/paddle_analysis_config.h). Holds the
    model path + execution options; GPU/TensorRT/MKLDNN toggles are accepted
    for API compatibility and mapped to their TPU/XLA meaning (or ignored
    where XLA always does the optimization)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # jit.save writes <path>.pdmodel/<path>.pdiparams; accept either the
        # bare prefix or the .pdmodel path.
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._ir_optim = True
        self._memory_optim = True
        self._device = "tpu"
        self._precision = None  # None = saved dtype; "bf16" casts params
        self._cpu_threads = 1

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file

    def model_dir(self) -> Optional[str]:
        return self._prefix

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag  # XLA always optimizes; kept for parity

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        self._device = "accelerator"  # any accelerator == default backend

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n: int):
        self._cpu_threads = n

    def enable_low_precision(self, dtype: str = "bf16"):
        """TPU analog of enable_use_gpu(precision=half)/TensorRT fp16."""
        self._precision = dtype

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix!r}, device={self._device}, "
                f"precision={self._precision or 'saved'})")


class Tensor:
    """Named input/output handle (ref: paddle_infer.Tensor /
    ZeroCopyTensor). copy_from_cpu stages a host array; copy_to_cpu
    materializes the device result."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.asarray(arr)

    def reshape(self, shape: Sequence[int]):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"output {self.name!r} not populated — "
                               "call predictor.run() first")
        return np.asarray(self._value)

    @property
    def shape(self):
        return None if self._value is None else tuple(self._value.shape)


class Predictor:
    """ref AnalysisPredictor: named-handle run protocol over the AOT
    executable."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        if not config.model_dir():
            raise ValueError("Config has no model path")
        self._config = config
        self._translated = jit_load(config.model_dir())
        n_in = self._n_model_inputs()
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs: Dict[str, Tensor] = {n: Tensor(n)
                                           for n in self._input_names}
        self._outputs: Dict[str, Tensor] = {}
        self._output_names: List[str] = []

    def _n_model_inputs(self) -> int:
        # Exported calling convention: (params_tree, buffers_tree, *xs).
        exported = self._translated._exported
        tree = exported.in_tree
        # in_tree is ((args...), kwargs); args = (params, buffers, *xs)
        n_args = tree.num_leaves  # leaves include params/buffers
        n_pb = (len(jax.tree_util.tree_leaves(self._translated._params)) +
                len(jax.tree_util.tree_leaves(self._translated._buffers)))
        # Remaining leaves are the example inputs.
        return max(1, n_args - n_pb)

    # -- handle protocol ---------------------------------------------------

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute. Either pass arrays positionally (returns outputs like
        the reference's predictor.run(inputs) overload) or stage them via
        get_input_handle(...).copy_from_cpu(...) first."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        xs = []
        for n in self._input_names:
            v = self._inputs[n]._value
            if v is None:
                raise RuntimeError(f"input {n!r} not set")
            xs.append(jnp.asarray(v))
        out = self._translated(*xs)
        flat = jax.tree_util.tree_leaves(out)
        self._output_names = [f"out{i}" for i in range(len(flat))]
        self._outputs = {}
        for n, v in zip(self._output_names, flat):
            t = Tensor(n)
            t.copy_from_cpu(np.asarray(v))
            self._outputs[n] = t
        if inputs is not None:
            return [self._outputs[n].copy_to_cpu()
                    for n in self._output_names]
        return True

    def clear_intermediate_tensor(self):
        pass  # XLA manages buffers

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    """ref: paddle_infer::CreatePredictor."""
    return Predictor(config)


class PredictorBenchmark:
    """Latency micro-bench (ref fluid/inference/utils/benchmark.h)."""

    def __init__(self, predictor: Predictor):
        self.predictor = predictor

    def run(self, inputs: Sequence[np.ndarray], warmup: int = 2,
            repeat: int = 10) -> Dict[str, float]:
        for _ in range(warmup):
            self.predictor.run(list(inputs))
        t0 = time.perf_counter()
        for _ in range(repeat):
            out = self.predictor.run(list(inputs))
        dt = (time.perf_counter() - t0) / repeat
        return {"latency_ms": dt * 1e3, "qps": (1.0 / dt) if dt else 0.0}
