from . import distributed  # noqa: F401
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import autotune  # noqa: F401


# -- top-level incubate exports (ref incubate/__init__.py __all__) ---------
from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402
from ..geometric import (segment_sum, segment_mean, segment_max,  # noqa: F401,E402
                         segment_min)


def softmax_mask_fuse(x, mask, name=None):
    """ref incubate softmax_mask_fuse: softmax(x + mask) in one pass
    (XLA fuses; the op exists for call-site parity)."""
    import jax
    import jax.numpy as jnp
    return jax.nn.softmax(jnp.asarray(x) + jnp.asarray(mask), axis=-1)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """ref softmax_mask_fuse_upper_triangle: causal-masked softmax on
    [B, H, S, S] scores (upper triangle masked)."""
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(x)
    s = x.shape[-1]
    mask = jnp.tril(jnp.ones((s, s), bool))
    return jax.nn.softmax(jnp.where(mask, x, -1e9), axis=-1)


def graph_send_recv(x, src_index, dst_index, pool_type: str = "sum",
                    out_size=None, name=None):
    """ref incubate graph_send_recv (now geometric.send_u_recv)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           flag_perm_buffer=False, name=None):
    """ref incubate graph_sample_neighbors (now geometric.sample_neighbors)."""
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """ref incubate graph_reindex (now geometric.reindex_graph)."""
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """ref incubate graph_khop_sampler: chained neighbor sampling over
    k hops (composed from sample_neighbors)."""
    from ..geometric import sample_neighbors
    import numpy as np
    nodes = np.asarray(input_nodes)
    all_rows, all_counts = [], []
    frontier = nodes
    for k in sample_sizes:
        out_neighbors, out_count = sample_neighbors(row, colptr, frontier,
                                                    sample_size=k)[:2]
        all_rows.append(out_neighbors)
        all_counts.append(out_count)
        frontier = np.unique(np.asarray(out_neighbors))
    import jax.numpy as jnp
    return (jnp.concatenate([jnp.asarray(r) for r in all_rows]),
            jnp.concatenate([jnp.asarray(c) for c in all_counts]),
            jnp.asarray(frontier))


def identity_loss(x, reduction="none"):
    """ref incubate.identity_loss (IPU loss anchor op): marks x as the
    loss; reduction in {none(0), sum(1), mean(2)}."""
    import jax.numpy as jnp
    x = jnp.asarray(x)
    red = {0: "sum", 1: "mean", 2: "none", "sum": "sum", "mean": "mean",
           "none": "none"}[reduction]
    if red == "sum":
        return jnp.sum(x)
    if red == "mean":
        return jnp.mean(x)
    return x
