"""Fused-op functional surface (``paddle.incubate.nn.functional`` parity).

Reference: ``python/paddle/incubate/nn/functional/`` backed by hand-written
CUDA megakernels (``fluid/operators/fused/fused_attention_op.cu``,
``fused_feedforward_op.cu``, ``fmha_ref.h``). TPU-native design: "fused"
is the compiler's job — these functions express the op sequence in one
traceable body; XLA fuses the elementwise/bias/dropout/residual/layernorm
chains into the surrounding matmuls, and attention cores route to the
Pallas flash kernel. The functions exist so reference callers keep a
1:1 API, with the same numerics.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...nn import functional as F
from ...ops.flash_attention import flash_attention

__all__ = [
    "fused_linear", "fused_matmul_bias", "fused_feedforward",
    "fused_multi_head_attention", "fused_bias_dropout_residual_layer_norm",
    "fused_rms_norm",
]


def fused_matmul_bias(x, y, bias=None, transpose_x: bool = False,
                      transpose_y: bool = False, name=None):
    """matmul + bias-add in one XLA fusion (ref
    ``incubate/nn/functional/fused_matmul_bias.py`` → cublasLt epilogue)."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = x @ y
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight: bool = False,
                 name=None):
    """ref ``incubate/nn/functional/fused_linear.py``."""
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True, mode: str = "upscale_in_train", name=None):
    """out = layer_norm(residual + dropout(x + bias)) (ref
    ``incubate/nn/functional/fused_transformer.py``)."""
    if bias is not None:
        x = x + bias
    x = F.dropout(x, dropout_rate, training=training, mode=mode)
    y = residual + x
    return F.layer_norm(y, y.shape[-1:], ln_scale, ln_bias, ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None,
                      dropout1_rate: float = 0.5, dropout2_rate: float = 0.5,
                      activation: str = "relu", ln1_epsilon: float = 1e-5,
                      ln2_epsilon: float = 1e-5, pre_layer_norm: bool = False,
                      training: bool = True, mode: str = "upscale_in_train",
                      name=None):
    """Transformer FFN block with residual + layernorm in one traced body
    (ref ``incubate/nn/functional/fused_transformer.py`` fused_feedforward):

    pre_layer_norm:  out = x + dropout2(W2 @ act(dropout1(W1 @ ln1(x))))
    post_layer_norm: out = ln2(x + dropout2(W2 @ act(dropout1(W1 @ x))))
    """
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], ln1_scale, ln1_bias, ln1_epsilon)
    act = getattr(F, activation)
    h = act(fused_linear(x, linear1_weight, linear1_bias))
    h = F.dropout(h, dropout1_rate, training=training, mode=mode)
    h = fused_linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], ln2_scale, ln2_bias,
                           ln2_epsilon)
    return out


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm: bool = False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon: float = 1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate: float = 0.5,
        attn_dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True, mode: str = "upscale_in_train",
        ring_id: int = -1, add_residual: bool = True, name=None):
    """Full attention residual block (ref fused_attention_op.cu via
    ``incubate/nn/functional/fused_transformer.py``).

    ``qkv_weight``: [3, num_heads, head_dim, embed_dim];
    ``qkv_bias``: [3, num_heads, head_dim]; ``linear_weight``:
    [embed_dim, embed_dim]. Attention core = flash attention (Pallas)
    when attention dropout is off, matching the reference's fmha path.
    """
    if cache_kv is not None:
        raise NotImplementedError(
            "decode-cache path: use nn.MultiHeadAttention with cache or "
            "FusedMultiTransformer's caches")
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    out = _qkv_attention_core(x, qkv_weight, qkv_bias, linear_weight,
                              linear_bias, attn_mask, attn_dropout_rate,
                              training, causal=False)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)
    return out


def _qkv_attention_core(x, qkv_weight, qkv_bias, linear_weight, linear_bias,
                        attn_mask, attn_dropout_rate, training,
                        causal: bool = False):
    """Fused-qkv attention shared by fused_multi_head_attention and
    FusedMultiTransformer: [3, H, D, E] weight -> one [E, 3HD] matmul,
    attention (flash when unmasked), output projection."""
    three, num_heads, head_dim, embed_dim = qkv_weight.shape
    if three != 3:
        raise ValueError(f"qkv_weight dim0 must be 3, got {three}")
    b, s, _ = x.shape
    w = jnp.transpose(qkv_weight, (3, 0, 1, 2)).reshape(embed_dim, -1)
    qkv = x @ w
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.reshape(-1)
    qkv = qkv.reshape(b, s, 3, num_heads, head_dim)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if attn_mask is not None:
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=causal,
            dropout_p=attn_dropout_rate, training=training)
    else:
        out = flash_attention(q, k, v, causal=causal,
                              dropout=attn_dropout_rate, training=training)
    out = out.reshape(b, s, num_heads * head_dim)
    return fused_linear(out, linear_weight, linear_bias)


def fused_rms_norm(x, norm_weight=None, norm_bias=None,
                   epsilon: float = 1e-6, begin_norm_axis: int = -1):
    """ref ``incubate/nn/functional/fused_rms_norm.py`` — on TPU the rms
    normalization chain is one XLA fusion already. Normalizes jointly over
    axes [begin_norm_axis, ndim), the reference semantics."""
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=axes, keepdims=True) + epsilon)
    out = (x32 / rms).astype(x.dtype)
    if norm_weight is not None:
        out = out * norm_weight.reshape(x.shape[axes[0]:])
    if norm_bias is not None:
        out = out + norm_bias.reshape(x.shape[axes[0]:])
    return out
