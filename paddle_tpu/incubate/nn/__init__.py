"""Fused transformer layers (``paddle.incubate.nn`` parity).

Reference: ``python/paddle/incubate/nn/layer/fused_transformer.py``
(FusedLinear/FusedFeedForward/FusedMultiHeadAttention/
FusedTransformerEncoderLayer/FusedBiasDropoutResidualLayerNorm over the CUDA
megakernels). Here each layer owns reference-shaped parameters and calls the
``incubate.nn.functional`` bodies, which XLA fuses and which route attention
through the Pallas flash kernel — the TPU analog of the fused ops.
"""

from __future__ import annotations

import math

from ...nn import initializer as I
from ...nn.layer import Layer
from ...ops.flash_attention import flash_attention
from . import functional as F

__all__ = ["FusedLinear", "FusedFeedForward", "FusedMultiHeadAttention",
           "FusedTransformerEncoderLayer",
           "FusedBiasDropoutResidualLayerNorm", "functional"]


class FusedLinear(Layer):
    """ref ``incubate/nn/layer/fused_linear.py`` (weight [in, out])."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 bias_attr=None, transpose_weight: bool = False, name=None):
        super().__init__()
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.transpose_weight = transpose_weight
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = (None if bias_attr is False else
                     self.create_parameter((out_features,), attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self.transpose_weight)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedBiasDropoutResidualLayerNorm``."""

    def __init__(self, embed_dim: int, dropout_rate: float = 0.5,
                 weight_attr=None, bias_attr=None, epsilon: float = 1e-5,
                 name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter((embed_dim,), attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            self.dropout_rate, self.epsilon, training=self.training)


class FusedMultiHeadAttention(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedMultiHeadAttention``.

    Parameters use the reference's fused layouts: qkv_weight
    [3, H, D, embed], linear_weight [embed, embed].
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout_rate: float = 0.5, attn_dropout_rate: float = 0.5,
                 kdim=None, vdim=None, normalize_before: bool = False,
                 need_weights: bool = False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon: float = 1e-5, nranks: int = 1, ring_id: int = -1,
                 name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"embed_dim ({embed_dim}) must be divisible by num_heads "
                f"({num_heads})")
        if (kdim and kdim != embed_dim) or (vdim and vdim != embed_dim):
            raise NotImplementedError("fused path requires k/v dim == embed")
        if need_weights:
            raise NotImplementedError(
                "need_weights=True is unsupported (the flash path never "
                "materializes attention probs); the reference raises too")
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        bound = 1.0 / math.sqrt(embed_dim)
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim), attr=qkv_weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.qkv_bias = (None if qkv_bias_attr is False else
                         self.create_parameter(
                             (3, num_heads, self.head_dim),
                             attr=qkv_bias_attr, is_bias=True))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear_bias = (None if linear_bias_attr is False else
                            self.create_parameter((embed_dim,),
                                                  attr=linear_bias_attr,
                                                  is_bias=True))
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), attr=pre_ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter((embed_dim,), is_bias=True,
                                                 attr=pre_ln_bias_attr)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True,
                                             attr=ln_bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if key is not None or value is not None:
            raise NotImplementedError("fused MHA is self-attention only")
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedFeedForward(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedFeedForward``."""

    def __init__(self, d_model: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, epsilon: float = 1e-5,
                 activation: str = "relu", act_dropout_rate=None,
                 normalize_before: bool = False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks: int = 1, ring_id: int = -1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear1_bias = (None if linear1_bias_attr is False else
                             self.create_parameter((dim_feedforward,),
                                                   attr=linear1_bias_attr,
                                                   is_bias=True))
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear2_bias = (None if linear2_bias_attr is False else
                             self.create_parameter((d_model,),
                                                   attr=linear2_bias_attr,
                                                   is_bias=True))
        self.ln1_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter((d_model,), is_bias=True,
                                              attr=ln1_bias_attr)
        self.ln2_scale = self.create_parameter(
            (d_model,), attr=ln2_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter((d_model,), is_bias=True,
                                              attr=ln2_bias_attr)

    def forward(self, x):
        return F.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, self.ln1_scale, self.ln1_bias, self.ln2_scale,
            self.ln2_bias, self.act_dropout_rate, self.dropout_rate,
            self.activation, self.epsilon, self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedTransformerEncoderLayer``."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, activation: str = "relu",
                 attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before: bool = False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class _FusedMTLayer(Layer):
    """One FusedMultiTransformer block: pre/post-LN attention + FFN with the
    reference's fused parameter layouts (qkv_weight [3, H, D, E]). The
    attention/FFN bodies are the shared fused functional paths."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate,
                 activation, normalize_before, epsilon, attrs):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        bound = 1.0 / math.sqrt(embed_dim)

        def bias(name, shape):
            a = attrs.get(name)
            return None if a is False else self.create_parameter(
                shape, is_bias=True, attr=a)

        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=attrs.get("ln_scale"),
            default_initializer=I.Constant(1.0))
        self.ln_bias = bias("ln_bias", (embed_dim,))
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim),
            attr=attrs.get("qkv_weight"),
            default_initializer=I.Uniform(-bound, bound))
        self.qkv_bias = bias("qkv_bias", (3, num_heads, self.head_dim))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=attrs.get("linear_weight"),
            default_initializer=I.XavierNormal())
        self.linear_bias = bias("linear_bias", (embed_dim,))
        self.ffn_ln_scale = self.create_parameter(
            (embed_dim,), attr=attrs.get("ffn_ln_scale"),
            default_initializer=I.Constant(1.0))
        self.ffn_ln_bias = bias("ffn_ln_bias", (embed_dim,))
        self.ffn1_weight = self.create_parameter(
            (embed_dim, dim_feedforward), attr=attrs.get("ffn1_weight"),
            default_initializer=I.XavierNormal())
        self.ffn1_bias = bias("ffn1_bias", (dim_feedforward,))
        self.ffn2_weight = self.create_parameter(
            (dim_feedforward, embed_dim), attr=attrs.get("ffn2_weight"),
            default_initializer=I.XavierNormal())
        self.ffn2_bias = bias("ffn2_bias", (embed_dim,))

    def _cached_attn(self, x, attn_mask, cache, time_step):
        """Incremental decode: append K/V at time_step, attend over the
        cache with the causal mask combined with any user mask."""
        import jax
        import jax.numpy as jnp

        from ...nn import functional as NF
        b, s, e = x.shape
        w = jnp.transpose(self.qkv_weight, (3, 0, 1, 2)).reshape(e, -1)
        qkv = x @ w
        if self.qkv_bias is not None:
            qkv = qkv + self.qkv_bias.reshape(-1)
        qkv = qkv.reshape(b, s, 3, self.num_heads, self.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_cache, v_cache = cache          # [b, max_len, H, D]
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k, (0, time_step, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v, (0, time_step, 0, 0))
        max_len = k_cache.shape[1]
        q_pos = time_step + jnp.arange(s)
        mask = (jnp.arange(max_len)[None, :]
                <= q_pos[:, None])[None, None]    # [1, 1, s, max_len] bool
        if attn_mask is not None:
            if attn_mask.dtype == jnp.bool_:
                mask = mask & attn_mask
            else:  # additive mask: fold ours into additive form
                mask = jnp.where(mask, 0.0, -jnp.inf) + attn_mask
        out = NF.scaled_dot_product_attention(
            q, k_cache, v_cache, attn_mask=mask, training=False)
        out = out.reshape(b, s, e) @ self.linear_weight
        if self.linear_bias is not None:
            out = out + self.linear_bias
        return out, (k_cache, v_cache)

    def forward(self, x, attn_mask=None, cache=None, time_step=0):
        from ...nn import functional as NF
        residual = x
        h = x
        if self.normalize_before:
            h = NF.layer_norm(h, (h.shape[-1],), self.ln_scale,
                              self.ln_bias, self.epsilon)
        if cache is not None:
            attn_out, new_cache = self._cached_attn(h, attn_mask, cache,
                                                    time_step)
        else:
            attn_out = F._qkv_attention_core(
                h, self.qkv_weight, self.qkv_bias, self.linear_weight,
                self.linear_bias, attn_mask, self.dropout_rate,
                self.training, causal=attn_mask is None)
            new_cache = None
        attn_out = NF.dropout(attn_out, self.dropout_rate,
                              training=self.training)
        h = residual + attn_out
        if not self.normalize_before:
            h = NF.layer_norm(h, (h.shape[-1],), self.ln_scale,
                              self.ln_bias, self.epsilon)
        # FFN body: the shared fused path (pre/post LN + residual inside).
        out = F.fused_feedforward(
            h, self.ffn1_weight, self.ffn2_weight, self.ffn1_bias,
            self.ffn2_bias,
            ln1_scale=self.ffn_ln_scale, ln1_bias=self.ffn_ln_bias,
            ln2_scale=self.ffn_ln_scale, ln2_bias=self.ffn_ln_bias,
            dropout1_rate=self.dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation, ln1_epsilon=self.epsilon,
            ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)
        return out, new_cache


class FusedMultiTransformer(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:1033`` — the fused
    multi-layer decoder stack used for LLM inference (one CUDA megakernel
    per layer there; one XLA fusion region + flash attention here).

    ``forward(src, attn_mask=None, caches=None, time_step=None)``:
    caches = per-layer (k, v) arrays [b, max_len, H, D] enables
    incremental decode at position ``time_step`` (a traced scalar is fine —
    the cache update is a dynamic_update_slice); returns (out, caches)
    when caches are given, else out.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate: float = 0.0, activation: str = "gelu",
                 normalize_before: bool = True, num_layers: int = -1,
                 epsilon: float = 1e-5, nranks: int = 1, ring_id: int = -1,
                 **per_layer_attrs):
        super().__init__()
        if num_layers <= 0:
            raise ValueError(f"num_layers must be positive, got {num_layers}")
        if embed_dim % num_heads:
            raise ValueError(
                f"embed_dim ({embed_dim}) must be divisible by num_heads "
                f"({num_heads})")

        def attr_for(i):
            out = {}
            for key, val in per_layer_attrs.items():
                if not key.endswith("_attrs"):
                    continue
                out[key[:-6]] = val[i] if isinstance(val, (list, tuple)) \
                    else val
            return out

        from ...nn.layers import LayerList
        self.layers = LayerList([
            _FusedMTLayer(embed_dim, num_heads, dim_feedforward,
                          dropout_rate, activation, normalize_before,
                          epsilon, attr_for(i))
            for i in range(num_layers)])
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads

    def gen_cache(self, batch: int, max_len: int, dtype=None):
        """Per-layer KV caches for incremental decode."""
        import jax.numpy as jnp
        shape = (batch, max_len, self.num_heads, self.head_dim)
        dtype = dtype or jnp.float32
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in self.layers]

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        h = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            h, new_cache = layer(
                h, attn_mask=attn_mask, cache=cache,
                time_step=0 if time_step is None else time_step)
            if caches is not None:
                new_caches.append(new_cache)
        if caches is not None:
            return h, new_caches
        return h


__all__ += ["FusedMultiTransformer"]


class FusedDropoutAdd(Layer):
    """ref incubate/nn/layer/fused_dropout_add.py: dropout(x) + y in one
    fused op (XLA fuses the pair; the layer exists for call-site parity
    and the seed/mode contract)."""

    def __init__(self, p: float = 0.5, mode: str = "upscale_in_train",
                 name=None):
        super().__init__()
        if mode not in ("upscale_in_train", "downscale_in_infer"):
            raise ValueError(f"unknown dropout mode {mode!r}")
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from ...nn import functional as F
        return F.dropout(x, self.p, training=self.training,
                         mode=self.mode) + y


class FusedEcMoe(Layer):
    """ref incubate/nn/layer/fused_ec_moe.py FusedEcMoe: expert-choice
    MoE — experts pick their top-C tokens (capacity-bounded, no token
    dropping decisions by tokens). One batched einsum pair over the
    expert dimension; gating via top-C per EXPERT."""

    def __init__(self, hidden_size: int, inter_size: int, num_experts: int,
                 act_type: str = "gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        from ...nn import initializer as I
        if act_type not in ("gelu", "relu"):
            raise ValueError(f"unsupported act_type {act_type!r}")
        self.act_type = act_type
        self.num_experts = num_experts
        self.gate = self.create_parameter((hidden_size, num_experts),
                                          attr=weight_attr)
        self.w1 = self.create_parameter((num_experts, hidden_size,
                                         inter_size), attr=weight_attr)
        self.b1 = self.create_parameter((num_experts, 1, inter_size),
                                        attr=bias_attr, is_bias=True)
        self.w2 = self.create_parameter((num_experts, inter_size,
                                         hidden_size), attr=weight_attr)
        self.b2 = self.create_parameter((num_experts, 1, hidden_size),
                                        attr=bias_attr, is_bias=True)

    def forward(self, x, gate_logits=None):
        import jax
        import jax.numpy as jnp
        b, s, h = x.shape
        tokens = x.reshape(b * s, h)
        logits = gate_logits.reshape(b * s, self.num_experts) \
            if gate_logits is not None else tokens @ self.gate
        n_tok = tokens.shape[0]
        capacity = max(n_tok // self.num_experts, 1)
        # expert-choice: each expert takes its top-capacity tokens
        scores = jax.nn.softmax(logits, axis=-1).T        # [E, T]
        top_s, top_idx = jax.lax.top_k(scores, capacity)  # [E, C]
        picked = tokens[top_idx]                          # [E, C, H]
        act = jax.nn.gelu if self.act_type == "gelu" else jax.nn.relu
        hidden = act(jnp.einsum("ech,ehi->eci", picked, self.w1) + self.b1)
        out_e = jnp.einsum("eci,eih->ech", hidden, self.w2) + self.b2
        out_e = out_e * top_s[..., None]
        # scatter-add expert outputs back to token slots
        out = jnp.zeros_like(tokens)
        out = out.at[top_idx.reshape(-1)].add(
            out_e.reshape(-1, h).astype(tokens.dtype))
        return out.reshape(b, s, h)


__all__ += ["FusedDropoutAdd", "FusedEcMoe"]
