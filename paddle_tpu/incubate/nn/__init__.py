"""Fused transformer layers (``paddle.incubate.nn`` parity).

Reference: ``python/paddle/incubate/nn/layer/fused_transformer.py``
(FusedLinear/FusedFeedForward/FusedMultiHeadAttention/
FusedTransformerEncoderLayer/FusedBiasDropoutResidualLayerNorm over the CUDA
megakernels). Here each layer owns reference-shaped parameters and calls the
``incubate.nn.functional`` bodies, which XLA fuses and which route attention
through the Pallas flash kernel — the TPU analog of the fused ops.
"""

from __future__ import annotations

import math

from ...nn import initializer as I
from ...nn.layer import Layer
from . import functional as F

__all__ = ["FusedLinear", "FusedFeedForward", "FusedMultiHeadAttention",
           "FusedTransformerEncoderLayer",
           "FusedBiasDropoutResidualLayerNorm", "functional"]


class FusedLinear(Layer):
    """ref ``incubate/nn/layer/fused_linear.py`` (weight [in, out])."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 bias_attr=None, transpose_weight: bool = False, name=None):
        super().__init__()
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.transpose_weight = transpose_weight
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = (None if bias_attr is False else
                     self.create_parameter((out_features,), attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self.transpose_weight)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedBiasDropoutResidualLayerNorm``."""

    def __init__(self, embed_dim: int, dropout_rate: float = 0.5,
                 weight_attr=None, bias_attr=None, epsilon: float = 1e-5,
                 name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter((embed_dim,), attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            self.dropout_rate, self.epsilon, training=self.training)


class FusedMultiHeadAttention(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedMultiHeadAttention``.

    Parameters use the reference's fused layouts: qkv_weight
    [3, H, D, embed], linear_weight [embed, embed].
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout_rate: float = 0.5, attn_dropout_rate: float = 0.5,
                 kdim=None, vdim=None, normalize_before: bool = False,
                 need_weights: bool = False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon: float = 1e-5, nranks: int = 1, ring_id: int = -1,
                 name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"embed_dim ({embed_dim}) must be divisible by num_heads "
                f"({num_heads})")
        if (kdim and kdim != embed_dim) or (vdim and vdim != embed_dim):
            raise NotImplementedError("fused path requires k/v dim == embed")
        if need_weights:
            raise NotImplementedError(
                "need_weights=True is unsupported (the flash path never "
                "materializes attention probs); the reference raises too")
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        bound = 1.0 / math.sqrt(embed_dim)
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim), attr=qkv_weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.qkv_bias = (None if qkv_bias_attr is False else
                         self.create_parameter(
                             (3, num_heads, self.head_dim),
                             attr=qkv_bias_attr, is_bias=True))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear_bias = (None if linear_bias_attr is False else
                            self.create_parameter((embed_dim,),
                                                  attr=linear_bias_attr,
                                                  is_bias=True))
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), attr=pre_ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter((embed_dim,), is_bias=True,
                                                 attr=pre_ln_bias_attr)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True,
                                             attr=ln_bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if key is not None or value is not None:
            raise NotImplementedError("fused MHA is self-attention only")
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedFeedForward(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedFeedForward``."""

    def __init__(self, d_model: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, epsilon: float = 1e-5,
                 activation: str = "relu", act_dropout_rate=None,
                 normalize_before: bool = False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks: int = 1, ring_id: int = -1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear1_bias = (None if linear1_bias_attr is False else
                             self.create_parameter((dim_feedforward,),
                                                   attr=linear1_bias_attr,
                                                   is_bias=True))
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear2_bias = (None if linear2_bias_attr is False else
                             self.create_parameter((d_model,),
                                                   attr=linear2_bias_attr,
                                                   is_bias=True))
        self.ln1_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter((d_model,), is_bias=True,
                                              attr=ln1_bias_attr)
        self.ln2_scale = self.create_parameter(
            (d_model,), attr=ln2_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter((d_model,), is_bias=True,
                                              attr=ln2_bias_attr)

    def forward(self, x):
        return F.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, self.ln1_scale, self.ln1_bias, self.ln2_scale,
            self.ln2_bias, self.act_dropout_rate, self.dropout_rate,
            self.activation, self.epsilon, self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """ref ``incubate/nn/layer/fused_transformer.py:FusedTransformerEncoderLayer``."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, activation: str = "relu",
                 attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before: bool = False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)
